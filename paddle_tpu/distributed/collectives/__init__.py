"""paddle_tpu.distributed.collectives — the hot-path collectives, owned.

Pre-PR, every training collective was implicit: the dp grad all-reduce
and the tp matmul seams were exactly what XLA's GSPMD emitted,
serialized after the backward. This subsystem makes communication a
first-class perf axis (ROADMAP item 2):

- **Quantized grad all-reduce** (:mod:`.quantized`): blockwise-int8
  (per-256-block scales, the same grid as ``memory/int8_ckpt``) with
  exact integer accumulation — EQuARX (PAPERS.md) reports negligible
  quality cost for gradient traffic. Applied to the dp gradient psum
  inside ``ShardedTrainStep`` with per-tensor opt-out (norms,
  embeddings, small tensors stay exact).
- **Bucketed backward-overlap** (:mod:`.overlap`): the grad tree
  partitions into size-bounded buckets, each reduced by its own
  collective so XLA can hide reduce time under remaining backward
  compute instead of serializing one tree-sized fusion after it.
- **Fused tp seams** (:mod:`.fused`): matmul+reduce-scatter and
  all-gather+matmul shard_map kernels for the row/col-parallel layers.

Knobs (docs/COMMS.md):

- ``PTPU_QUANT_COLLECTIVES`` (default on): master switch. ``=0`` is the
  bitwise-parity escape hatch — every path in this package disengages
  and the compiled step is byte-identical to the pre-PR program.
- ``PTPU_QUANT_GRADS`` (default on): int8 for the dp grad reduce
  specifically (off = exact psum, still bucketed/overlapped).
- ``PTPU_COMM_BUCKET_MB`` / ``PTPU_QUANT_MIN_NUMEL`` /
  ``PTPU_QUANT_EXCLUDE``: bucket bound and the exact-tensor opt-out.
- ``PTPU_TP_SEAM``: ``auto`` | ``fused`` | ``0`` (see :mod:`.fused`).

Knobs are read when a step BUILDS (never per call), so toggling the env
between calls cannot recompile — asserted by the recompile-invariance
test.
"""
from __future__ import annotations

import contextlib
import os

import jax.numpy as jnp

from ... import telemetry as _telemetry
from .quantized import (  # noqa: F401
    QUANT_BLOCK,
    packed_int32_psum,
    quantize_shared_scale_int8,
    quantized_all_reduce_rs_ag,
    quantized_psum,
    quantized_wire_bytes,
)
from .overlap import (  # noqa: F401
    DEFAULT_BUCKET_MB,
    DEFAULT_MIN_QUANT_NUMEL,
    EXACT_NAME_FRAGMENTS,
    GradBucket,
    GradReducePlan,
    bucket_bytes_cap,
    is_exact_grad,
    min_quant_numel,
    partition_buckets,
    reduce_grads,
)
from .fused import (  # noqa: F401
    TPSeamPlan,
    plan_tp_seams,
    tp_seam_mode,
)
from .zero import (  # noqa: F401
    ZeroParam,
    ZeroPlan,
    build_zero_plan,
    jit_gather_enabled,
    param_gather_quantized,
    resolve_stage,
    zero_mode_enabled,
)
from .ring_attention import (  # noqa: F401
    RingAttnPlan,
    build_ring_attn_plan,
    ring_attn_enabled,
    ring_parity_probe,
)

__all__ = [
    "quant_collectives_enabled", "grads_quantized", "manual_grad_region",
    "in_manual_grad_region", "build_grad_reduce_plan", "note_grad_reduce",
    "quantized_psum", "quantized_all_reduce_rs_ag", "packed_int32_psum",
    "partition_buckets", "reduce_grads", "GradReducePlan", "GradBucket",
    "plan_tp_seams", "TPSeamPlan", "comms_summary", "parity_probe",
    "PARITY_THRESHOLD", "ZeroPlan", "ZeroParam", "build_zero_plan",
    "resolve_stage", "zero_mode_enabled", "note_zero_step",
    "RingAttnPlan", "build_ring_attn_plan", "ring_attn_enabled",
    "ring_parity_probe", "note_ring_attn",
]


def quant_collectives_enabled():
    """Master switch (``PTPU_QUANT_COLLECTIVES``, default ON). ``=0``
    must reproduce the pre-PR step bitwise — every consumer checks this
    FIRST."""
    return os.environ.get("PTPU_QUANT_COLLECTIVES", "1") not in ("0", "off")


def grads_quantized():
    """int8 for the dp grad reduce (``PTPU_QUANT_GRADS``, default ON;
    master switch must also be on)."""
    return (quant_collectives_enabled()
            and os.environ.get("PTPU_QUANT_GRADS", "1") not in ("0", "off"))


# -- manual-region tracing flag --------------------------------------------
# This XLA cannot nest gather/scatter shard_map islands inside a
# manual-subgroup region (spmd_partitioner CHECK failure), so code that
# would open one (the fused tp seams, the sharded CE head) must know it
# is being traced inside the quantized dp-grad region. Legacy jax's
# get_abstract_mesh shim reports an always-empty mesh, so the region is
# tracked explicitly here; tracing is single-threaded per call.
_MANUAL_REGION_DEPTH = [0]


@contextlib.contextmanager
def manual_grad_region():
    _MANUAL_REGION_DEPTH[0] += 1
    try:
        yield
    finally:
        _MANUAL_REGION_DEPTH[0] -= 1


def in_manual_grad_region():
    return _MANUAL_REGION_DEPTH[0] > 0


# -- telemetry --------------------------------------------------------------
# same-registry families as distributed/communication (labelnames must
# match across definition sites — the registry rejects a mismatch)
_COLL_CALLS = _telemetry.counter(
    "collective_calls_total", "eager collective API calls",
    labelnames=("op", "axis", "nranks"))
_COLL_BYTES = _telemetry.counter(
    "collective_bytes_total", "payload bytes entering eager collectives",
    labelnames=("op", "axis", "nranks"))
_COLL_SECONDS = _telemetry.histogram(
    "collective_seconds", "wall time per collective entry",
    labelnames=("op", "axis"))
_COLL_QBYTES = _telemetry.counter(
    "collective_quantized_bytes_total",
    "payload bytes that rode an int8-quantized collective (the same "
    "basis as collective_bytes_total: bytes ENTERING the reduce, so the "
    "exact/quantized split sums to total traffic)",
    labelnames=("op", "axis"))


def note_quantized_bytes(op, axis, nbytes):
    """Count payload bytes that rode an int8 collective (same basis as
    collective_bytes_total, so exact = total - quantized)."""
    if _telemetry.get_registry().enabled and nbytes:
        _COLL_QBYTES.inc(int(nbytes), labels=(op, axis))


def _trace_reduce_collectives(plan):
    """One trace instant per planned grad-reduce collective for this
    executed step, labeled op/axis/bytes/quantized from the plan's
    static summary (docs/TELEMETRY.md Tracing) — the timeline view of
    the same accounting the counters aggregate. ZeroPlans emit through
    ``_trace_zero_collectives`` instead (their collectives are gathers
    and reduce-scatters, not bucket reduces)."""
    tr = _telemetry.trace
    buckets = getattr(plan, "buckets", None)
    if not tr.enabled() or not buckets:
        return
    for i, b in enumerate(buckets):
        tr.instant("collective:grad_reduce",
                   {"op": "grad_reduce", "axis": plan.axis_label,
                    "nranks": plan.nranks, "bucket": i,
                    "bytes": int(b.payload_bytes),
                    "quantized": bool(b.quantized)}, cat="comms")


def _trace_zero_collectives(plan):
    """Trace instants for one executed ZeRO step: a param-gather and/or
    grad reduce-scatter event per parameter, labeled kind/bytes/
    quantized from the ZeroParam recipes (docs/ZERO.md traffic basis)."""
    tr = _telemetry.trace
    if not tr.enabled():
        return
    ax = plan.shard_axis
    for p in plan.params:
        if p.kind == "dim":
            tr.instant("collective:param_gather",
                       {"op": "all_gather", "axis": ax, "param": p.name,
                        "bytes": int(p.nbytes),
                        "quantized": bool(plan.gather_quantized)},
                       cat="comms")
            tr.instant("collective:grad_rs",
                       {"op": "reduce_scatter", "axis": ax,
                        "param": p.name, "bytes": int(p.nbytes),
                        "quantized": False}, cat="comms")
        elif p.kind == "flat":
            tr.instant("collective:grad_rs",
                       {"op": "reduce_scatter", "axis": ax,
                        "param": p.name, "bytes": int(p.nbytes),
                        "quantized": bool(p.quantized)}, cat="comms")
            tr.instant("collective:param_gather",
                       {"op": "all_gather", "axis": ax, "param": p.name,
                        "bytes": int(p.padded
                                     * jnp.dtype(p.dtype).itemsize),
                        "quantized": False}, cat="comms")
        else:  # replicated: the exact full psum, PR 6 semantics
            tr.instant("collective:grad_reduce",
                       {"op": "psum", "axis": plan.axis_label,
                        "param": p.name, "bytes": int(p.nbytes),
                        "quantized": False}, cat="comms")


def note_grad_reduce(plan):
    """Tick the per-step comms accounting for one executed grad-reduce
    plan (host side; the payload sizes are static per plan). Accepts
    either a GradReducePlan or the duck-typed ZeroPlan."""
    if plan is not None:
        _trace_reduce_collectives(plan)
    if not _telemetry.get_registry().enabled or plan is None:
        return
    labels3 = ("grad_reduce", plan.axis_label, str(plan.nranks))
    _COLL_CALLS.inc(plan.calls, labels=labels3)
    _COLL_BYTES.inc(plan.exact_bytes + plan.quantized_payload_bytes,
                    labels=labels3)
    if plan.quantized_payload_bytes:
        _COLL_QBYTES.inc(plan.quantized_payload_bytes,
                         labels=("grad_reduce", plan.axis_label))


# ZeRO traffic (docs/ZERO.md, docs/TELEMETRY.md): gathered param bytes
# and reduce-scattered grad bytes per step, on the same static-per-plan
# host-side basis as the grad_reduce counters above. "quantized" labels
# whether that traffic rode the int8 wire format.
_ZERO_GATHER = _telemetry.counter(
    "zero3_param_gather_bytes_total",
    "full-parameter bytes materialized by ZeRO just-in-time gathers "
    "(stage-3 dim-shard gathers + stage-2 post-update chunk gathers)",
    labelnames=("axis", "quantized"))
_ZERO_RS = _telemetry.counter(
    "zero3_grad_rs_bytes_total",
    "gradient bytes entering a ZeRO reduce-scatter (payload basis, like "
    "collective_bytes_total)",
    labelnames=("axis", "quantized"))


def note_zero_step(plan):
    """Tick the per-step ZeRO traffic accounting for one executed step
    under an engaged ZeroPlan (no-op for GradReducePlan/None). A
    ComposedPlan (collectives/compose) carries its inner zero plan on
    ``.zero`` — the composed step's zero traffic rides the same basis."""
    from .zero import ZeroPlan

    if plan is not None and not isinstance(plan, ZeroPlan):
        plan = getattr(plan, "zero", None)
    if not isinstance(plan, ZeroPlan):
        return
    _trace_zero_collectives(plan)
    if not _telemetry.get_registry().enabled:
        return
    ax = plan.shard_axis
    # only the stage-3 dim gathers can ride the int8 wire
    # (PTPU_QUANT_PARAM_GATHER); the stage-2 post-update chunk gathers
    # are always exact — label them separately or the -- zero -- report
    # would overstate int8 traffic
    if plan.dim_gather_bytes:
        _ZERO_GATHER.inc(plan.dim_gather_bytes,
                         labels=(ax, "1" if plan.gather_quantized else "0"))
    if plan.flat_gather_bytes:
        _ZERO_GATHER.inc(plan.flat_gather_bytes, labels=(ax, "0"))
    rs_q = sum(p.nbytes for p in plan.params
               if p.kind == "flat" and p.quantized)
    rs_exact = plan.grad_rs_bytes - rs_q
    if rs_exact:
        _ZERO_RS.inc(rs_exact, labels=(ax, "0"))
    if rs_q:
        _ZERO_RS.inc(rs_q, labels=(ax, "1"))


# Ring-attention KV rotation traffic (docs/ATTENTION.md,
# docs/TELEMETRY.md): bytes of KV (fwd) and KV+grad-accumulator (bwd)
# blocks rotated around the sep ring per executed step — the same
# static-per-plan host-side basis as note_grad_reduce.
_RING_KV = _telemetry.counter(
    "ring_attn_kv_bytes_total",
    "KV block bytes rotated around the sep ring per executed step "
    "(phase=fwd: k+v over n-1 hops; phase=bwd: k+v over n-1 hops plus "
    "the traveling dk/dv accumulators over n hops — the final hop "
    "carries only the accumulators home; 4B/elem payload basis)",
    labelnames=("axis", "phase"))


def note_ring_attn(plan):
    """Tick the per-step ring-attention traffic accounting for one
    executed step under an engaged RingAttnPlan (no-op for None or a
    plan whose trace never routed attention)."""
    if plan is None or not plan.seq_local:
        return
    tr = _telemetry.trace
    if tr.enabled():
        hop_bytes = plan.kv_block_bytes * plan.layers
        for hop in range(1, plan.sep_degree):
            tr.instant("collective:ring_attn",
                       {"op": "ppermute", "axis": plan.axis,
                        "phase": "fwd", "hop": hop, "bytes": hop_bytes},
                       cat="comms")
        for hop in range(plan.sep_degree):
            last = hop == plan.sep_degree - 1
            tr.instant("collective:ring_attn",
                       {"op": "ppermute", "axis": plan.axis,
                        "phase": "bwd", "hop": hop,
                        "bytes": hop_bytes if last else 2 * hop_bytes},
                       cat="comms")
    if not _telemetry.get_registry().enabled:
        return
    if plan.fwd_rotate_bytes:
        _RING_KV.inc(plan.fwd_rotate_bytes, labels=(plan.axis, "fwd"))
    if plan.bwd_rotate_bytes:
        _RING_KV.inc(plan.bwd_rotate_bytes, labels=(plan.axis, "bwd"))


def build_grad_reduce_plan(named_params, mesh, *, exclude_axes=(),
                           quantized=None, bucket_bytes=None,
                           reason_out=None):
    """Build the dp-grad reduce plan for a ShardedTrainStep, or None
    (``reason_out``, when given, receives the structured decline
    :class:`~.compose.Reason`).

    ``named_params``: [(name, shape, dtype)] in reduce (state-dict)
    order. Engages only when it is provably safe AND worthwhile on this
    runtime:

    - master knob on;
    - the live mesh axes are a subset of {dp, sharding, mp} (pipeline /
      context-parallel / expert meshes keep the GSPMD path — their
      kernels open their own manual regions, which cannot nest here);
    - at least one data axis (dp/sharding) is live, shards the batch,
      and is named by NO parameter placement (ZeRO-3 'sharding'
      placements stay with GSPMD);
    - at least one gradient actually quantizes (tiny models keep the
      exact pre-PR program byte-for-byte — nothing to win there).
    """
    from .compose import Reason
    from .compose import note_decline as _note

    if not quant_collectives_enabled():
        return _note(reason_out, Reason.MASTER_OFF)
    if quantized is None:
        quantized = grads_quantized()
    live = {a: mesh.get_dim_size(a) for a in mesh.dim_names
            if mesh.get_dim_size(a) > 1}
    if not live or not set(live) <= {"dp", "sharding", "mp"}:
        return _note(reason_out, Reason.MESH_AXES)
    axes = tuple(a for a in ("dp", "sharding")
                 if a in live and a not in exclude_axes)
    if not axes:
        return _note(reason_out, Reason.NO_DATA_AXIS)
    buckets = partition_buckets(named_params, bucket_bytes=bucket_bytes,
                                quantized=quantized)
    if not any(b.quantized for b in buckets):
        return _note(reason_out, Reason.NO_QUANTIZABLE_GRAD)
    nranks = 1
    for a in axes:
        nranks *= live[a]
    return GradReducePlan(axes=axes, nranks=nranks, buckets=buckets)


# -- reporting --------------------------------------------------------------
#: quantized-vs-exact parity gate. The probe normalizes |quant - exact|
#: by nranks * shared_block_absmax — the quantization GRID, which is
#: what theory bounds: each rank rounds by at most half a step
#: (shared_absmax/254), so the summed error is <= 1/254 ~ 0.0039 of the
#: grid. Threshold at 1/127 leaves 2x headroom; anything past it means
#: the quantizer itself regressed.
PARITY_THRESHOLD = 1.0 / 127


def comms_summary(snapshot, plan=None, parity=None):
    """Assemble the bench/dryrun ``"comms"`` block from a telemetry
    snapshot: bytes/calls/seconds per op+axis plus the exact-vs-int8
    traffic split (docs/COMMS.md contract)."""
    counters = (snapshot or {}).get("counters") or {}
    hists = (snapshot or {}).get("histograms") or {}

    def _series(name):
        return counters.get(name) or {}

    def _op_axis(labels):
        d = dict(p.split("=", 1) for p in labels.split(",") if "=" in p)
        return f"{d.get('op', '?')}@{d.get('axis', '?')}"

    per_op = {}
    for name, field in (("collective_bytes_total", "bytes"),
                        ("collective_calls_total", "calls")):
        for labels, v in _series(name).items():
            row = per_op.setdefault(_op_axis(labels), {})
            row[field] = row.get(field, 0) + int(v)
    for labels, h in (hists.get("collective_seconds") or {}).items():
        row = per_op.setdefault(_op_axis(labels), {})
        row["seconds_sum"] = float(h.get("sum", 0.0))
        row["seconds_p50"] = float(h.get("p50", 0.0))
    total = sum(op.get("bytes", 0) for op in per_op.values())
    qtotal = sum(int(v)
                 for v in _series("collective_quantized_bytes_total").values())
    out = {
        "enabled": quant_collectives_enabled(),
        "per_op": per_op,
        "bytes_total": int(total),
        "quantized_bytes_total": int(qtotal),
        "exact_bytes_total": int(total - qtotal),
        "quantized_fraction": (float(qtotal) / total) if total else 0.0,
    }
    if plan is not None:
        out["grad_reduce"] = plan.summary()
    if parity is not None:
        out["parity"] = parity
    return out


def parity_probe(mesh=None, axis=None, *, numel=1 << 14, seed=0):
    """Quantized-vs-exact loss-parity probe: reduce a skewed/outlier
    gradient surrogate over a live mesh axis with BOTH kernels and
    report the max per-block relative error plus wall times. The bench
    attaches the result to its "comms" block; ``tools/bench_gate.py``
    fails the round when ``max_rel_err > threshold``."""
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None:
        from ..fleet import active_mesh

        mesh = active_mesh()
    if mesh is None or not quant_collectives_enabled():
        return {"enabled": False}
    if axis is None:
        axis = next((a for a in ("dp", "sharding")
                     if a in mesh.dim_names and mesh.get_dim_size(a) > 1),
                    None)
    if axis is None:
        return {"enabled": False}
    n = mesh.get_dim_size(axis)
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n, numel)).astype(np.float32)
    data[:, rng.integers(0, numel, max(numel // 256, 1))] *= 1000.0  # outliers
    sharding = NamedSharding(mesh.jax_mesh, PartitionSpec(axis))
    arr = jax.device_put(jnp.asarray(data), sharding)

    def _q(b):
        return quantized_psum(b[0], (axis,), n)[None]

    def _e(b):
        return jax.lax.psum(b[0], (axis,))[None]

    spec = PartitionSpec(axis)
    kw = dict(mesh=mesh.jax_mesh, in_specs=(spec,), out_specs=spec,
              check_vma=False, axis_names={axis})
    qf = jax.jit(shard_map(_q, **kw))
    ef = jax.jit(shard_map(_e, **kw))
    qv = np.asarray(qf(arr))[0]          # compile + run
    ev = np.asarray(ef(arr))[0]
    t0 = time.perf_counter()
    qf(arr).block_until_ready()
    tq = time.perf_counter() - t0
    t0 = time.perf_counter()
    ef(arr).block_until_ready()
    te = time.perf_counter() - t0
    # error relative to the shared quantization GRID (nranks * the
    # cross-rank per-block absmax) — the quantity theory bounds; the
    # exact SUM's magnitude is not (cancellation shrinks it arbitrarily)
    blk = QUANT_BLOCK if numel % QUANT_BLOCK == 0 else 1
    shared_amax = np.abs(data).reshape(n, -1, blk).max(axis=(0, 2))
    diff = np.abs(qv - ev).reshape(-1, blk).max(axis=1)
    err = float((diff / np.maximum(n * shared_amax, 1e-6)).max())
    return {
        "enabled": True, "axis": axis, "nranks": n, "numel": numel,
        "max_rel_err": err, "threshold": PARITY_THRESHOLD,
        "ok": err <= PARITY_THRESHOLD,
        "quantized_seconds": tq, "exact_seconds": te,
    }
