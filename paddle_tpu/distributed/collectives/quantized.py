"""Quantized all-reduce kernels (EQuARX-style, arXiv:2506.17615).

Two lowerings of the same contract — ``psum(x)`` over a named mesh axis
with blockwise-int8 wire format and EXACT integer accumulation:

- :func:`quantized_psum` — shared-scale int8 + lane-packed int32 psum.
  The per-256-block scales are pmax-shared across ranks first, so every
  rank's int8 codes live on one grid and the cross-rank sum can ride a
  single integer AllReduce (two 8-bit lanes biased into each int32 word,
  carry-free for <=128 ranks). AllReduce is the ONLY collective this
  path emits, which makes it safe inside partial-auto (manual-subgroup)
  ``shard_map`` regions: this XLA build hard-crashes the SPMD
  partitioner on AllGather/ReduceScatter/CollectivePermute with manual
  subgroups (the same limitation behind the pre-existing pipeline test
  failures), but AllReduce lowers fine. This is the kernel the
  ``ShardedTrainStep`` dp-grad reduce uses.

- :func:`quantized_all_reduce_rs_ag` — the full EQuARX decomposition:
  quantize -> reduce-scatter with int32 accumulation -> dequant ->
  re-quantize -> all-gather. ~1 byte/element on the wire in BOTH phases
  (vs 2 for bf16, 4 for f32) at the cost of a second quantization
  round-trip. Requires a FULLY-manual region (every mesh axis manual),
  which is where ReduceScatter/AllGather lower correctly here — the
  eager collective API's 1-D group meshes qualify, and on TPU runtimes
  whose partitioner handles manual subgroups it is the preferred
  in-step lowering too (``PTPU_QUANT_IMPL=rsag``).

Both kernels bound the per-element error by ``block_absmax / 127`` per
quantization phase (one phase for the psum kernel, two for rs+ag); the
shared-scale psum kernel's integer accumulation adds NO further error.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: block length for the per-block absmax scales — matches the int8
#: activation-checkpoint blocks (memory/int8_ckpt.INT8_BLOCK)
QUANT_BLOCK = 256

#: lane packing rides two biased 8-bit codes per int32 word; the hi
#: lane's worst-case sum is 255 * nranks * 2**16, which must stay under
#: int32 — carry-free through 128 ranks
_PACK_MAX_RANKS = 128


def _blockify(x, block):
    """Flatten to f32 [nb, block] (zero-padded) + (shape, dtype, numel)."""
    shape, dtype = x.shape, x.dtype
    xf = x.astype(jnp.float32).reshape(-1)
    n = xf.size
    pad = (-n) % block
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad,), jnp.float32)])
    return xf.reshape(-1, block), (shape, dtype, n)


def _unblockify(xb, meta):
    shape, dtype, n = meta
    return xb.reshape(-1)[:n].reshape(shape).astype(dtype)


def quantize_shared_scale_int8(x, axis_names, block=QUANT_BLOCK):
    """Blockwise int8 with ONE scale grid shared by every rank on
    ``axis_names`` (per-block absmax pmax'd across ranks). Must run
    inside a shard_map region where those axes are manual. Returns
    (q int32 codes in [-127, 127], scale f32 [nb, 1], meta)."""
    xb, meta = _blockify(x, block)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    amax = jax.lax.pmax(amax, axis_names)
    scale = jnp.maximum(amax / 127.0, 1e-30)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int32)
    return q, scale, meta


def _pack_lanes_default():
    """Lane packing halves the AllReduce payload on a real interconnect
    but is pure extra arithmetic when the "wire" is an in-process memcpy
    — default ON for accelerator backends, OFF for the CPU host-platform
    simulation. ``PTPU_QUANT_PACK=1/0`` forces."""
    import os

    env = os.environ.get("PTPU_QUANT_PACK", "")
    if env:
        return env not in ("0", "off")
    return jax.default_backend() not in ("cpu",)


def packed_int32_psum(q, axis_names, nranks, pack=None):
    """psum int8-range codes (as int32) over ``axis_names``, packing two
    biased lanes per int32 word when carry-free (nranks <= 128 and an
    even trailing dim) — halves the AllReduce payload vs raw int32."""
    if pack is None:
        pack = _pack_lanes_default()
    if not pack or nranks > _PACK_MAX_RANKS or q.shape[-1] % 2:
        return jax.lax.psum(q, axis_names)
    qb = q + 128                                   # [1, 255]: lanes stay >= 0
    packed = qb[..., 1::2] * 65536 + qb[..., 0::2]
    s = jax.lax.psum(packed, axis_names)
    lo = s % 65536 - 128 * nranks
    hi = s // 65536 - 128 * nranks
    out = jnp.stack([lo, hi], axis=-1)             # [..., half, 2]
    return out.reshape(q.shape)


def quantized_psum(x, axis_names, nranks, *, block=QUANT_BLOCK, mean=False):
    """Shared-scale blockwise-int8 psum of ``x`` over manual
    ``axis_names``. AllReduce-only lowering (partial-auto safe); exact
    int32 accumulation; per-element error <= shared_block_absmax/127.
    ``mean=True`` folds the 1/nranks into the pre-quantization scaling so
    the shared scales see the final magnitudes."""
    if mean:
        x = x / nranks
    q, scale, meta = quantize_shared_scale_int8(x, axis_names, block)
    s = packed_int32_psum(q, axis_names, nranks)
    return _unblockify(s.astype(jnp.float32) * scale, meta)


def quantized_all_reduce_rs_ag(x, axis_name, nranks, *, block=QUANT_BLOCK,
                               mean=False):
    """EQuARX pipeline: int8 quantize -> reduce-scatter (int32 accum) ->
    dequant -> re-quantize -> all-gather -> dequant. FULLY-manual regions
    only (see module docstring); ~1 byte/element wire format per phase."""
    if mean:
        x = x / nranks
    # pad so the block grid splits evenly into nranks scatter chunks
    xb, meta = _blockify(x, block)
    nb = xb.shape[0]
    pad_rows = (-nb) % nranks
    if pad_rows:
        xb = jnp.concatenate(
            [xb, jnp.zeros((pad_rows, block), jnp.float32)])
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    amax = jax.lax.pmax(amax, axis_name)
    scale = jnp.maximum(amax / 127.0, 1e-30)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    # int32-accumulated reduce-scatter: rank r receives the exact integer
    # sums of its block-row chunk (127 * nranks stays far inside int32)
    ssum = jax.lax.psum_scatter(q.astype(jnp.int32), axis_name,
                                scatter_dimension=0, tiled=True)
    # this rank's rows of the SHARED scale grid, without lax.axis_index
    # (PartitionId does not lower on every runtime): scatter-summing a
    # replicated value yields nranks * my_rows
    my_scale = jax.lax.psum_scatter(scale, axis_name, scatter_dimension=0,
                                    tiled=True) / nranks
    chunk = ssum.astype(jnp.float32) * my_scale
    # phase 2: re-quantize the reduced chunk for the gather
    amax2 = jnp.maximum(jnp.max(jnp.abs(chunk), axis=-1, keepdims=True),
                        1e-30)
    s2 = amax2 / 127.0
    q2 = jnp.clip(jnp.round(chunk / s2), -127, 127).astype(jnp.int8)
    qg = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    out = qg.astype(jnp.float32) * sg
    if pad_rows:
        out = out[:nb]
    return _unblockify(out, meta)


def quantized_wire_bytes(numel, nranks, *, block=QUANT_BLOCK, impl="psum"):
    """Approximate per-rank wire bytes one quantized reduce moves, for
    the telemetry split (docs/COMMS.md). psum: 2 B/elem packed-int32
    AllReduce + the f32 scale grid; rsag: ~1 B/elem per phase."""
    nb = (int(numel) + block - 1) // block
    scales = nb * 4
    if impl == "rsag":
        return 2 * int(numel) + 2 * scales
    payload = int(numel) * (2 if nranks <= _PACK_MAX_RANKS else 4)
    return payload + scales
