"""Bucketed backward-overlap for the dp gradient reduce.

The pre-PR step leaves the dp grad all-reduce entirely to GSPMD, which
emits ONE fused psum over the whole flattened grad tree — it cannot
start until the LAST gradient of the backward walk exists, so reduce
time serializes after compute ("Optimizing Distributed ML Communication
with Fused Computation-Collective Operations", PAPERS.md, motivates
breaking exactly this barrier). Here the grad tree is partitioned into
size-bounded buckets (paddle parity: EagerReducer's comm_buffer_size
bucketing, reducer.h:88) and each bucket is reduced by its OWN
collective whose operands are only that bucket's grads — the dataflow
lets XLA's scheduler issue a bucket's reduce as soon as its gradients
are produced in the backward walk, hiding it under the remaining
backward compute instead of after it.

Caveat (honest): for the scan-over-layers ``StackedDecoder`` every
stacked parameter's gradient finishes only when the backward scan
completes, so cross-layer overlap needs the unrolled path
(``PTPU_UNROLL_LAYERS``); bucket separation still overlaps the embedding
/head/norm reduces with the decoder backward, and caps the collective's
working-set vs one tree-sized fusion.

Buckets are split by (exact-vs-quantized, dtype) so exact buckets psum
in their native dtype — elementwise identical to per-tensor psum, which
the parity tests check bitwise.
"""
from __future__ import annotations

import dataclasses
import os
import re

import jax
import jax.numpy as jnp

from .quantized import QUANT_BLOCK, quantized_psum, quantized_wire_bytes

#: default bucket bound (MB) — mirrors the reference DataParallel
#: comm_buffer_size=25 default, rounded to a power of two
DEFAULT_BUCKET_MB = 32

#: grads smaller than this quantize poorly relative to their collective's
#: latency cost — they stay exact (norms/biases are also name-excluded)
DEFAULT_MIN_QUANT_NUMEL = 65536

#: name fragments whose tensors always reduce exactly (ISSUE: "norms,
#: embeddings stay exact")
EXACT_NAME_FRAGMENTS = ("norm", "ln", "bias", "embed", "lm_head", "scale")


def bucket_bytes_cap():
    mb = float(os.environ.get("PTPU_COMM_BUCKET_MB", DEFAULT_BUCKET_MB))
    return int(mb * 2**20) if mb > 0 else 0


def min_quant_numel():
    return int(os.environ.get("PTPU_QUANT_MIN_NUMEL",
                              DEFAULT_MIN_QUANT_NUMEL))


def is_exact_grad(name, shape, dtype=None):
    """Per-tensor opt-out: small/sensitive tensors reduce exactly.
    ``PTPU_QUANT_EXCLUDE`` appends comma-separated name fragments."""
    numel = 1
    for d in shape:
        numel *= int(d)
    if numel < min_quant_numel() or len(shape) <= 1:
        return True
    frags = EXACT_NAME_FRAGMENTS + tuple(
        f for f in os.environ.get("PTPU_QUANT_EXCLUDE", "").split(",") if f)
    low = name.lower()
    return any(f in low for f in frags)


@dataclasses.dataclass(frozen=True)
class GradBucket:
    names: tuple          # leaf names, reduce order
    numels: tuple         # flattened element counts, aligned with names
    dtype: str
    quantized: bool

    @property
    def numel(self):
        return sum(self.numels)

    @property
    def payload_bytes(self):
        """Bytes ENTERING the reduce (the pre-PR exact cost basis)."""
        return self.numel * jnp.dtype(self.dtype).itemsize


@dataclasses.dataclass(frozen=True)
class GradReducePlan:
    """Static description of one step's dp-grad reduce, built once at
    TrainStep build time (parallel_step._build_reduce_plan): which mesh
    axes are manual, and how the grad tree partitions into buckets.

    Under ``sharding_stage >= 2`` on a pure-data mesh the step builds a
    :class:`~.zero.ZeroPlan` instead — it duck-types this accounting
    surface (calls/bytes/summary) and additionally reduce-SCATTERS each
    bucket into the dp-sharded update's layout (docs/ZERO.md)."""
    axes: tuple           # manual mesh axis names the reduce runs over
    nranks: int
    buckets: tuple        # GradBucket, issue order
    quant_block: int = QUANT_BLOCK

    @property
    def axis_label(self):
        return "+".join(self.axes)

    @property
    def exact_bytes(self):
        return sum(b.payload_bytes for b in self.buckets if not b.quantized)

    @property
    def quantized_payload_bytes(self):
        return sum(b.payload_bytes for b in self.buckets if b.quantized)

    @property
    def quantized_wire_bytes(self):
        return sum(
            quantized_wire_bytes(b.numel, self.nranks, block=self.quant_block)
            for b in self.buckets if b.quantized)

    @property
    def calls(self):
        return len(self.buckets)

    def summary(self):
        """JSON-able shape for the bench/dryrun "comms" block."""
        return {
            "axes": list(self.axes), "nranks": self.nranks,
            "buckets": len(self.buckets),
            "quantized_buckets": sum(1 for b in self.buckets if b.quantized),
            "exact_bytes": int(self.exact_bytes),
            "quantized_payload_bytes": int(self.quantized_payload_bytes),
            "quantized_wire_bytes": int(self.quantized_wire_bytes),
            "quantized_fraction": (
                float(self.quantized_payload_bytes)
                / float(self.exact_bytes + self.quantized_payload_bytes)
                if self.buckets else 0.0),
        }


#: layer-index fragment in a per-layer parameter name
#: ("model.layers.3.attn.q_proj.weight" -> family
#: "model.layers.*.attn.q_proj.weight")
_LAYER_IDX_RE = re.compile(r"(?<=\.)\d+(?=\.)")


def slab_grouping_enabled():
    """``PTPU_COMM_SLAB=1``: group per-layer grad leaves of the same
    weight family into ONE bucket per slab (docs/SCAN.md). The scanned
    eager model keeps per-layer parameter leaves while the stacked
    flagship carries one [L, ...] leaf per weight kind — slab grouping
    makes the per-layer tree's reduce plan match the stacked tree's
    (one collective per slab, one per non-layer tensor) so the wire
    behavior doesn't depend on which layout the model stores. Off by
    default: the size-capped partition below is the measured r6 plan."""
    return os.environ.get("PTPU_COMM_SLAB", "") not in ("", "0")


def _slab_key(name):
    # wildcard ONLY the first (layer) index: a second index (MoE
    # expert ordinals, "...layers.3.mlp.experts.5.weight") stays
    # literal — in the stacked layout each expert is its own [L, ...]
    # leaf, so each expert must be its own slab family too
    return _LAYER_IDX_RE.sub("*", name, count=1)


def _partition_slabs(named_shapes, quantized):
    """One GradBucket per (weight family, exactness, dtype), first-seen
    order; non-layer-indexed tensors are their own single-leaf family
    (mirroring the stacked layout, where each slab IS one leaf)."""
    fams = {}
    order = []
    for name, shape, dtype in named_shapes:
        numel = 1
        for d in shape:
            numel *= int(d)
        dt = str(jnp.dtype(dtype))
        q = quantized and not is_exact_grad(name, shape, dtype)
        key = (_slab_key(name), q, dt)
        if key not in fams:
            fams[key] = []
            order.append(key)
        fams[key].append((name, numel))
    return tuple(
        GradBucket(names=tuple(n for n, _ in fams[k]),
                   numels=tuple(m for _, m in fams[k]),
                   dtype=k[2], quantized=k[1])
        for k in order)


def partition_buckets(named_shapes, bucket_bytes=None, quantized=True,
                      slab=None):
    """Partition ``[(name, shape, dtype), ...]`` (reduce order) into
    size-bounded :class:`GradBucket`\\ s. Consecutive leaves of the same
    (exactness, dtype) share a bucket up to ``bucket_bytes``; an
    oversized leaf gets its own bucket (never split — the collective
    granularity is a whole tensor). ``bucket_bytes=0`` = one bucket per
    tensor. ``slab`` (default: ``PTPU_COMM_SLAB``) switches to one
    bucket per per-layer weight family — see
    :func:`slab_grouping_enabled`."""
    if slab is None:
        slab = slab_grouping_enabled()
    if slab:
        return _partition_slabs(named_shapes, quantized)
    if bucket_bytes is None:
        bucket_bytes = bucket_bytes_cap()
    buckets, cur, cur_bytes, cur_key = [], [], 0, None
    quant_on = quantized

    def flush():
        nonlocal cur, cur_bytes
        if cur:
            q, dt = cur_key
            buckets.append(GradBucket(
                names=tuple(n for n, _ in cur),
                numels=tuple(m for _, m in cur), dtype=dt, quantized=q))
        cur, cur_bytes = [], 0

    for name, shape, dtype in named_shapes:
        numel = 1
        for d in shape:
            numel *= int(d)
        dt = str(jnp.dtype(dtype))
        q = quant_on and not is_exact_grad(name, shape, dtype)
        nbytes = numel * jnp.dtype(dtype).itemsize
        key = (q, dt)
        if cur and (key != cur_key
                    or (bucket_bytes and cur_bytes + nbytes > bucket_bytes)):
            flush()
        cur_key = key
        cur.append((name, numel))
        cur_bytes += nbytes
        if not bucket_bytes or cur_bytes >= bucket_bytes:
            flush()  # bucket_bytes=0: one collective per tensor
    flush()
    return tuple(buckets)


def reduce_grads(grads, plan, *, mean=True):
    """Apply the planned bucketed reduce to a ``{name: grad}`` tree.

    Runs PER-SHARD inside the manual region of ``plan.axes`` — each
    bucket's leaves are flattened into one contiguous operand and reduced
    by one collective (exact psum in the native dtype, or the
    shared-scale int8 psum kernel). ``mean=True`` divides by nranks (the
    dp-mean convention matching d(global mean loss)/dparam)."""
    out = dict(grads)
    inv = 1.0 / plan.nranks
    for bucket in plan.buckets:
        flats = [grads[n].reshape(-1) for n in bucket.names]
        buf = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        if bucket.quantized:
            red = quantized_psum(buf, plan.axes, plan.nranks,
                                 block=plan.quant_block, mean=mean)
        else:
            red = jax.lax.psum(buf, plan.axes)
            if mean:
                red = (red * jnp.asarray(inv, jnp.float32).astype(red.dtype)
                       if jnp.issubdtype(red.dtype, jnp.floating)
                       else red // plan.nranks)
        off = 0
        for name, numel in zip(bucket.names, bucket.numels):
            out[name] = red[off:off + numel].reshape(grads[name].shape)
            off += numel
    return out
