"""True ZeRO execution mode: reduce-scattered grads, dp-sharded weight
update, just-in-time parameter gathers (docs/ZERO.md).

Pre-PR, ``group_sharded_parallel(level="p_g_os")`` only stamped
``Shard(0)`` placements and hoped GSPMD did something reasonable: the
grad reduce stayed a full all-reduce, optimizer slots replicated on the
hot path, and the PR 6 :class:`~.overlap.GradReducePlan` explicitly
declined any param sharded over a data axis. This module is the real
thing — the blueprint is "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (PAPERS.md) plus the EQuARX int8
reduce-scatter (PR 6, :mod:`.quantized`):

- **Stage 3** (``p_g_os``): params stay RESIDENT as their GSPMD dim
  shards (``shard_model_parameters`` placements). Inside the step's
  fully-manual region they are all-gathered just-in-time for the
  forward — the stacked decoder's ``[L, ...]`` weight slabs gather
  per-layer INSIDE the ``lax.scan`` body (:func:`jit_gather_scope`,
  models/gpt.py), so layer *l+1*'s slab gather can overlap layer *l*'s
  compute when the scan is unrolled >= 2 wide. AD of the gather IS the
  reduce-scatter (``all_gather`` transposes to ``psum_scatter``), so
  every sharded param's gradient arrives already scattered into its
  1/degree dim slice — exact, f32 — and the optimizer update runs
  directly on the shard with param-shaped, dp-sharded slots.
- **Stage 2** (``os_g``): params keep replicated storage; each grad
  tensor is reduce-SCATTERED into a flat 1/degree chunk (the EQuARX
  int8 integer-accumulated scatter for quantizable tensors — bitwise
  identical to the replicated int8 all-reduce because integer sums are
  order-free; full psum + static slice for exact tensors — same
  summation order as the replicated path), the update runs on the
  chunk against flat dp-sharded slots, and the updated chunks
  all-gather back into full params.

Numerics contract (proven float32-hex in tests/test_zero3.py on the
1xN CPU mesh): engaging stage 2 or stage 3 changes NOTHING versus the
replicated data-parallel manual path — same per-shard loss, same grad
values, same update bytes. ``PTPU_QUANT_COLLECTIVES=0`` (the PR 6
master escape hatch) disengages the whole mode and restores the pre-PR
GSPMD placement-hint program byte-for-byte; ``PTPU_ZERO_MODE=0``
disengages just this mode while keeping the PR 6 replicated plan
eligible.
"""
from __future__ import annotations

import contextlib
import dataclasses
import functools
import os

import jax
import jax.numpy as jnp

from .overlap import is_exact_grad
from .quantized import QUANT_BLOCK, _blockify, quantize_shared_scale_int8

#: group_sharded_parallel level -> ZeRO stage
STAGE_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def zero_mode_enabled():
    """The zero execution mode rides behind BOTH the PR 6 master switch
    (``PTPU_QUANT_COLLECTIVES=0`` must reproduce the pre-PR program
    byte-for-byte, and the pre-PR stage-3 program is the GSPMD
    placement-hint path) and its own ``PTPU_ZERO_MODE`` knob."""
    from . import quant_collectives_enabled

    if not quant_collectives_enabled():
        return False
    return os.environ.get("PTPU_ZERO_MODE", "1") not in ("0", "off")


def jit_gather_enabled():
    """``PTPU_ZERO_JIT_GATHER`` (default on): defer stacked-decoder slab
    gathers into the scan body (fsdp-style; remat re-gathers in
    backward). ``=0`` gathers every param up front instead — the layout
    and numerics are identical (proven hex in tests), only the gather
    timing moves."""
    return os.environ.get("PTPU_ZERO_JIT_GATHER", "1") not in ("0", "off")


def param_gather_quantized():
    """``PTPU_QUANT_PARAM_GATHER=1``: ride the stage-3 param gathers on
    the PR 6 int8 all-gather (codes + f32 scales on the wire, ~1B/elem).
    Default OFF — unlike gradient traffic, int8 params perturb the
    forward, so the exact gather is the default and the bitwise-parity
    contract. Master switch (``PTPU_QUANT_COLLECTIVES``) also gates.

    Stacking rule (docs/QUANT.md): with the knob UNSET and quantized
    compute force-engaged (``PTPU_QUANT_COMPUTE`` truthy), the int8
    gathers ride along — the forward already runs narrow scaled GEMMs,
    so int8 param perturbation is inside the mode's numerics contract
    and stage-3 traffic halves for free. An explicit ``0``/``off``
    always wins."""
    from . import quant_collectives_enabled

    if not quant_collectives_enabled():
        return False
    env = os.environ.get("PTPU_QUANT_PARAM_GATHER", "")
    if env not in ("", "0", "off"):
        return True
    if env in ("0", "off"):
        return False
    from ...quant import quant_compute_forced

    return quant_compute_forced()


def flat_padded_len(numel, degree, *, quantized, block=QUANT_BLOCK):
    """Padded flat length for a stage-2 chunk-sharded tensor. Quantized
    tensors pad to the int8 block GRID (the scatter moves whole
    [block]-rows, keeping the shared-scale grid identical to the
    replicated ``quantized_psum`` — the bitwise-parity invariant);
    exact tensors pad only to the shard degree."""
    numel = int(numel)
    degree = int(degree)
    if quantized:
        nb = -(-numel // block)
        nb = -(-nb // degree) * degree
        return nb * block
    return -(-numel // degree) * degree


@dataclasses.dataclass(frozen=True)
class ZeroParam:
    """Per-parameter shard recipe inside a :class:`ZeroPlan`.

    kind:
    - ``dim``: storage-sharded (stage 3 GSPMD placement, ``shard_dim``
      over the shard axis). Gathered in-region (up front, or in the
      scan body when ``deferred_attr`` names a StackedDecoder slab);
      grads arrive as exact dim slices via AD; slots are param-shaped
      and follow the param's placement.
    - ``flat``: storage-replicated, update-sharded (stage 2, and
      stage-3 params with no divisible dim). Grad reduce-scatters into
      a flat chunk (int8 when ``quantized``); slots are flat
      ``[padded]`` arrays sharded over the shard axis; the updated
      chunks all-gather back to a full param.
    - ``replicated``: tiny tensors — exact psum + replicated update,
      exactly the PR 6 path.
    """
    name: str
    kind: str
    shape: tuple
    dtype: str
    numel: int
    shard_dim: int | None = None
    deferred_attr: str | None = None
    quantized: bool = False
    padded: int | None = None
    spec: object | None = None      # PartitionSpec of the dim storage

    @property
    def nbytes(self):
        return self.numel * jnp.dtype(self.dtype).itemsize


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static description of one step's ZeRO execution, resolved at
    TrainStep build time (knobs read at BUILD, never per call). Duck-
    types the :class:`~.overlap.GradReducePlan` accounting surface so
    ``note_grad_reduce`` / the bench "comms" block work unchanged, and
    adds the zero accounting behind the bench "zero" block."""
    stage: int
    axes: tuple            # live data axes (the reduce axes)
    shard_axis: str        # the axis params/slots/chunks shard over
    shard_degree: int
    nranks: int            # product over axes (the grad-mean divisor)
    params: tuple          # ZeroParam, state-dict order
    gather_quantized: bool = False
    quant_block: int = QUANT_BLOCK

    @functools.cached_property
    def by_name(self):
        return {p.name: p for p in self.params}

    @property
    def dp_axes(self):
        return tuple(a for a in self.axes if a != self.shard_axis)

    # -- GradReducePlan-compatible accounting (docs/COMMS.md basis:
    # payload bytes ENTERING each grad collective) ----------------------
    @property
    def axis_label(self):
        return "+".join(self.axes)

    @property
    def calls(self):
        return len(self.params)

    @property
    def exact_bytes(self):
        return sum(p.nbytes for p in self.params if not p.quantized)

    @property
    def quantized_payload_bytes(self):
        return sum(p.nbytes for p in self.params if p.quantized)

    @property
    def quantized_wire_bytes(self):
        """~1B/elem int8 codes + the f32 scale grid per quantized
        reduce-scatter (the EQuARX rs phase; docs/ZERO.md)."""
        total = 0
        for p in self.params:
            if p.quantized:
                nb = -(-p.numel // self.quant_block)
                total += p.numel + 4 * nb
        return total

    # -- zero accounting -------------------------------------------------
    @property
    def dim_gather_bytes(self):
        """Full-param bytes of the stage-3 ``dim`` gathers per step (one
        forward gather per dim param; the scan-deferred slabs re-gather
        in the remat backward — counted once here; the telemetry basis
        is gathered bytes OUT of the collective). This is the traffic
        ``PTPU_QUANT_PARAM_GATHER`` moves onto the int8 wire."""
        return sum(p.nbytes for p in self.params if p.kind == "dim")

    @property
    def flat_gather_bytes(self):
        """Padded bytes of the stage-2 post-update chunk all-gathers —
        always the exact wire (the quantized-gather knob only covers
        dim gathers; updated WEIGHTS must reassemble bitwise)."""
        return sum(p.padded * jnp.dtype(p.dtype).itemsize
                   for p in self.params if p.kind == "flat")

    @property
    def param_gather_bytes(self):
        """Full-param bytes materialized by gathers per step: dim
        forward gathers + flat post-update chunk gathers."""
        return self.dim_gather_bytes + self.flat_gather_bytes

    @property
    def grad_rs_bytes(self):
        """Grad bytes entering a reduce-scatter (dim-kind AD scatters +
        flat quantized scatters; exact flat/replicated tensors ride a
        full psum and are not counted here)."""
        return sum(p.nbytes for p in self.params
                   if p.kind == "dim" or (p.kind == "flat" and p.quantized))

    def counts(self):
        out = {"dim": 0, "flat": 0, "replicated": 0, "deferred": 0}
        for p in self.params:
            out[p.kind] += 1
            if p.deferred_attr:
                out["deferred"] += 1
        return out

    def zero_summary(self):
        """JSON-able shape for the bench ``"zero"`` block."""
        return {
            "stage": self.stage,
            "shard_axis": self.shard_axis,
            "shard_degree": self.shard_degree,
            "axes": list(self.axes),
            "engaged": True,
            "params": self.counts(),
            "param_gather_bytes_per_step": int(self.param_gather_bytes),
            "grad_rs_bytes_per_step": int(self.grad_rs_bytes),
            "quantized_param_gather": bool(self.gather_quantized),
        }

    def summary(self):
        """GradReducePlan-shaped comms summary + the zero block."""
        qp = self.quantized_payload_bytes
        eb = self.exact_bytes
        return {
            "axes": list(self.axes), "nranks": self.nranks,
            "buckets": self.calls,
            "quantized_buckets": sum(1 for p in self.params if p.quantized),
            "exact_bytes": int(eb),
            "quantized_payload_bytes": int(qp),
            "quantized_wire_bytes": int(self.quantized_wire_bytes),
            "quantized_fraction": (float(qp) / float(eb + qp)
                                   if (eb + qp) else 0.0),
            "zero": self.zero_summary(),
        }


def resolve_stage(optimizer, explicit=None):
    """ZeRO stage: an explicit ``sharding_stage`` wins; else the
    ``group_sharded_parallel`` level mark on the optimizer; else 0."""
    if explicit is not None:
        return int(explicit)
    level = getattr(optimizer, "_group_sharded_level", None)
    return STAGE_LEVELS.get(level, 0)


def build_zero_plan(named_entries, mesh, stage, *, optimizer=None,
                    grad_clip=None, deferred=None, reason_out=None):
    """Resolve the ZeRO execution plan for a ShardedTrainStep, or None
    (``reason_out``, when given, receives the structured
    :class:`~.compose.Reason` for a decline).

    ``named_entries``: ``[(name, tensor)]`` for the trainable params in
    state-dict order. Engages only when provably safe on this runtime:

    - stage >= 2 and the mode knobs on (:func:`zero_mode_enabled`);
    - the live mesh axes are a subset of {dp, sharding} — a live mp/pp/
      sep/ep axis keeps the GSPMD path (the fully-manual region this
      mode needs cannot nest their kernels' own manual regions, and
      partial-auto regions reject gather/scatter on this XLA,
      docs/COMMS.md runtime limits);
    - the optimizer's update is elementwise (factored/int8-moment
      variants compute cross-element statistics that are wrong on a
      shard) and grad clip is not the per-tensor-norm variant;
    - param placements are consistent with the stage (stage-2 marks
      with data-axis param shards fall back to GSPMD).
    """
    from .compose import Reason
    from .compose import note_decline as _note

    if stage < 2:
        return _note(reason_out, Reason.STAGE_LT_2)
    if not zero_mode_enabled():
        from . import quant_collectives_enabled

        return _note(reason_out,
                     Reason.MASTER_OFF if not quant_collectives_enabled()
                     else Reason.ZERO_MODE_OFF)
    live = {a: mesh.get_dim_size(a) for a in mesh.dim_names
            if mesh.get_dim_size(a) > 1}
    if not live or not set(live) <= {"dp", "sharding"}:
        return _note(reason_out, Reason.MESH_AXES)
    shard_axis = "sharding" if "sharding" in live else "dp"
    degree = live[shard_axis]
    if degree <= 1:
        return _note(reason_out, Reason.NO_DATA_AXIS)
    if optimizer is not None and (
            getattr(optimizer, "_factored", False)
            or getattr(optimizer, "_moment_dtype", None)):
        return _note(reason_out, Reason.OPTIMIZER_STATS)
    from ...nn.clip import ClipGradByNorm

    if isinstance(grad_clip, ClipGradByNorm):
        # per-tensor norms need the full grad tensor
        return _note(reason_out, Reason.CLIP_BY_NORM)
    from . import grads_quantized
    from ..auto_parallel import Shard, placements_to_spec

    deferred = deferred or {}
    quant = grads_quantized()
    jit_gather = jit_gather_enabled()
    params = []
    nranks = 1
    for a in live:
        nranks *= live[a]
    for name, t in named_entries:
        arr = t._data
        shape = tuple(int(d) for d in arr.shape)
        numel = 1
        for d in shape:
            numel *= d
        dtype = str(jnp.dtype(arr.dtype))
        da = getattr(t, "_dist_attr", None)
        sdim = None
        spec = None
        if da is not None:
            for ax_name, pl in zip(da.process_mesh.dim_names, da.placements):
                if not isinstance(pl, Shard):
                    continue
                if ax_name == shard_axis:
                    sdim = pl.dim
                elif da.process_mesh.get_dim_size(ax_name) > 1:
                    # sharded over an axis this plan can't own
                    return _note(reason_out, Reason.MESH_AXES)
            if sdim is not None:
                spec = placements_to_spec(da.process_mesh, da.placements)
        if sdim is not None:
            if stage < 3:
                # stage-2 marks + stage-3 placements: GSPMD
                return _note(reason_out, Reason.ZERO3_PLACEMENT)
            attr = deferred.get(name)
            params.append(ZeroParam(
                name, "dim", shape, dtype, numel, shard_dim=sdim,
                deferred_attr=(attr if (attr and sdim >= 1 and jit_gather)
                               else None),
                spec=spec))
        elif numel >= degree and shape and jnp.issubdtype(
                jnp.dtype(dtype), jnp.inexact):
            q = quant and not is_exact_grad(name, shape, dtype)
            params.append(ZeroParam(
                name, "flat", shape, dtype, numel, quantized=q,
                padded=flat_padded_len(numel, degree, quantized=q)))
        else:
            params.append(ZeroParam(name, "replicated", shape, dtype, numel))
    if not any(p.kind in ("dim", "flat") for p in params):
        return _note(reason_out, Reason.NO_SHARDABLE_STATE)
    return ZeroPlan(stage=stage,
                    axes=tuple(a for a in ("dp", "sharding") if a in live),
                    shard_axis=shard_axis, shard_degree=degree,
                    nranks=nranks, params=tuple(params),
                    gather_quantized=param_gather_quantized())


# ---------------------------------------------------------------------------
# In-region collectives (all called per-shard inside the fully-manual
# shard_map region the ShardedTrainStep opens)
# ---------------------------------------------------------------------------
def _q_gather_impl(x, axis_name, dim, degree, block):
    # the PR 6 int8 grid, via the shared helpers (NOT an inline copy —
    # the wire format must stay byte-compatible with quantized.py's):
    # _blockify pads the flat shard to [nb, block], and the scale recipe
    # matches quantize_shared_scale_int8 / quantized_all_reduce_rs_ag
    # (amax/127 clamped at 1e-30) — here per-SOURCE-shard, no pmax,
    # since each rank publishes its own shard's codes
    xb, (shard_shape, dtype, n) = _blockify(x, block)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-30)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    qg = jax.lax.all_gather(q, axis_name, tiled=False)       # [S, nb, B]
    sg = jax.lax.all_gather(scale, axis_name, tiled=False)   # [S, nb, 1]
    deq = (qg.astype(jnp.float32) * sg).reshape(degree, -1)[:, :n]
    pieces = [deq[i].reshape(shard_shape).astype(dtype)
              for i in range(degree)]
    return jnp.concatenate(pieces, axis=dim)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _q_gather(x, axis_name, dim, degree, block):
    return _q_gather_impl(x, axis_name, dim, degree, block)


def _q_gather_fwd(x, axis_name, dim, degree, block):
    return _q_gather_impl(x, axis_name, dim, degree, block), None


def _q_gather_bwd(axis_name, dim, degree, block, _res, g):
    # backward = the EXACT gather's transpose (psum_scatter to this
    # rank's dim slice): jnp.round's zero derivative must not kill the
    # gathered params' gradients, and keeping the grad reduce exact is
    # the same wide-backward discipline as the int8 FFN saves (the
    # output dtype equals the shard dtype, so no cast is needed)
    return (jax.lax.psum_scatter(g, axis_name, scatter_dimension=dim,
                                 tiled=True),)


_q_gather.defvjp(_q_gather_fwd, _q_gather_bwd)


def gather_shard(x, axis_name, dim, *, degree=None, quantized=False,
                 block=QUANT_BLOCK):
    """All-gather a dim-sharded value back to its full shape.

    Exact (default): one tiled ``all_gather`` over ``axis_name`` at
    ``dim`` — reconstructs the original bytes exactly, and AD transposes
    it to the ``psum_scatter`` that IS the stage-3 grad reduce.

    ``quantized=True`` (``PTPU_QUANT_PARAM_GATHER``): the PR 6 int8
    all-gather phase — each rank quantizes its shard blockwise (codes +
    f32 scales on the wire, ~1B/elem), the codes gather, and the full
    value dequantizes per source shard. The backward is hand-written as
    the exact gather's transpose (``psum_scatter``), so gradients stay
    exact while only the forward weights ride int8."""
    if not quantized:
        return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
    if degree is None:
        raise ValueError("quantized gather_shard needs the shard degree")
    return _q_gather(x, axis_name, dim, degree, block)


def _mean_scale(red, inv, nranks):
    """The exact-bucket mean convention of ``overlap.reduce_grads`` —
    reused verbatim so zero-mode exact reduces are bitwise identical to
    the replicated plan's."""
    if jnp.issubdtype(red.dtype, jnp.floating):
        return red * jnp.asarray(inv, jnp.float32).astype(red.dtype)
    return red // nranks


def reduce_grad(g, zp, plan, ordinal, *, mean=True):
    """Reduce one param's gradient into its update layout (per-shard).

    - ``dim``: AD already reduce-scattered over the shard axis; psum the
      remaining data axes and apply the mean scale.
    - ``flat`` quantized: shared-scale int8 (the SAME flat grid as the
      replicated ``quantized_psum`` — pmax over ALL reduce axes), int32
      codes psum over dp then psum_scatter over the shard axis (integer
      accumulation: bitwise-equal to the replicated all-reduce chunk),
      dequantized against this rank's scale rows.
    - ``flat`` exact: full psum in the replicated path's summation
      order, then a static chunk slice — parity over wire savings for
      the opted-out tensors (their slots still shard).
    - ``replicated``: the PR 6 exact per-tensor psum.
    """
    axes = plan.axes
    inv = 1.0 / plan.nranks
    if zp.kind == "dim":
        dp = plan.dp_axes
        if dp:
            g = jax.lax.psum(g, dp)
        return _mean_scale(g, inv, plan.nranks) if mean else g
    if zp.kind == "replicated":
        red = jax.lax.psum(g.reshape(-1), axes)
        if mean:
            red = _mean_scale(red, inv, plan.nranks)
        return red.reshape(zp.shape)
    # flat
    S = plan.shard_degree
    chunk = zp.padded // S
    if zp.quantized:
        x = g.reshape(-1)
        if mean:
            x = x / plan.nranks
        q, scale, _meta = quantize_shared_scale_int8(x, axes,
                                                     plan.quant_block)
        nb = q.shape[0]
        nb_pad = zp.padded // plan.quant_block
        if nb_pad > nb:
            q = jnp.pad(q, ((0, nb_pad - nb), (0, 0)))
            scale = jnp.pad(scale, ((0, nb_pad - nb), (0, 0)))
        dp = plan.dp_axes
        if dp:
            q = jax.lax.psum(q, dp)
        qc = jax.lax.psum_scatter(q, plan.shard_axis, scatter_dimension=0,
                                  tiled=True)
        rows = nb_pad // S
        sc = jax.lax.dynamic_slice(
            scale, (ordinal * rows, jnp.zeros((), ordinal.dtype)), (rows, 1))
        return (qc.astype(jnp.float32) * sc).reshape(-1).astype(g.dtype)
    red = jax.lax.psum(g.reshape(-1), axes)
    if mean:
        red = _mean_scale(red, inv, plan.nranks)
    if zp.padded > zp.numel:
        red = jnp.pad(red, (0, zp.padded - zp.numel))
    return jax.lax.dynamic_slice(red, (ordinal * chunk,), (chunk,))


def update_view(params, plan, ordinal):
    """Param values in the UPDATE layout: dim shards pass through (they
    enter the region as their storage shard), flat params slice this
    rank's padded chunk, replicated pass through."""
    out = {}
    for zp in plan.params:
        p = params[zp.name]
        if zp.kind == "flat":
            chunk = zp.padded // plan.shard_degree
            flat = p.reshape(-1)
            if zp.padded > zp.numel:
                flat = jnp.pad(flat, (0, zp.padded - zp.numel))
            out[zp.name] = jax.lax.dynamic_slice(
                flat, (ordinal * chunk,), (chunk,))
        else:
            out[zp.name] = p
    return out


def params_out(new_upd, plan):
    """Updated values back in the STORAGE layout: flat chunks all-gather
    into full params (replicated storage); dim shards and replicated
    params pass through."""
    out = {}
    for zp in plan.params:
        v = new_upd[zp.name]
        if zp.kind == "flat":
            full = jax.lax.all_gather(v, plan.shard_axis, axis=0, tiled=True)
            out[zp.name] = full[:zp.numel].reshape(zp.shape)
        else:
            out[zp.name] = v
    return out


def global_grad_sumsq(grads, plan):
    """f32 sum of squares over the (mixed-layout) grad tree: sharded
    leaves (dim slices + flat chunks — already fully reduced over dp,
    partitioned over the shard axis; flat pad rows are zero) psum over
    the shard axis; replicated leaves count once."""
    local = jnp.zeros((), jnp.float32)
    repl = jnp.zeros((), jnp.float32)
    any_sharded = False
    for zp in plan.params:
        g = grads.get(zp.name)
        if g is None:
            continue
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        if zp.kind == "replicated":
            repl = repl + s
        else:
            any_sharded = True
            local = local + s
    if any_sharded:
        repl = repl + jax.lax.psum(local, (plan.shard_axis,))
    return repl


# ---------------------------------------------------------------------------
# Just-in-time slab gathers: the scan-body seam (models/gpt.py)
# ---------------------------------------------------------------------------
# The ShardedTrainStep sets this scope while tracing its per-shard body;
# StackedDecoder._run consults it and gathers each sharded [L, ...] slab
# slice INSIDE the (remat-wrapped) scan block instead of receiving full
# weights — the fsdp recipe: resident state is the shard, the full layer
# weights exist only transiently per layer, and the remat backward
# re-gathers instead of saving them. Tracing is single-threaded per
# process (same discipline as collectives.manual_grad_region).
_JIT_GATHERS = [None]


@contextlib.contextmanager
def jit_gather_scope(info):
    """``info``: {stacked-attr: (axis_name, stacked_dim, degree,
    quantized)} for the slabs whose gathers are deferred into the scan
    body; None/empty clears."""
    prev = _JIT_GATHERS[0]
    _JIT_GATHERS[0] = dict(info) if info else None
    try:
        yield
    finally:
        _JIT_GATHERS[0] = prev


def active_jit_gathers():
    return _JIT_GATHERS[0]
