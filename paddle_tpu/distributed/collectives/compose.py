"""Hybrid-mesh composition: ONE manual-region program over dp×mp(×pp).

Pre-PR, every high-value plan in this package was an engage-or-decline
ISLAND: :class:`~.overlap.GradReducePlan` and :class:`~.zero.ZeroPlan`
engaged only on pure-data meshes, the fused tp seams only on
pipeline-free meshes outside the grad region, and the compiled pipeline
schedules opened a partial-manual shard_map this container's XLA cannot
lower at all when another axis is live (CollectivePermute with manual
subgroups hard-aborts the partitioner). The 3-axis hybrid bench
therefore ran the plain GSPMD program with NONE of the quantized /
overlapped / ZeRO machinery.

This module replaces the per-plan islands with an explicit
**compatibility lattice** (:data:`COMPAT_LATTICE`,
:func:`build_composed_plan`) and a :class:`ComposedPlan` that runs the
whole step — forward, loss, backward, grad reduce, sharded update —
inside ONE fully-manual ``shard_map`` region over every live axis:

- **TP seams** (:class:`ManualSeams`): the PR 6 matmul+reduce-scatter /
  all-gather+matmul kernels re-expressed as per-shard ``custom_vjp``
  calls over the manual ``mp`` axis (identical per-shard math to
  :mod:`.fused`'s island bodies; the weight-grad data-axis psum moves
  into the bucketed reduce below). The residual stream between seams is
  SEQUENCE-SHARDED over mp; :meth:`ManualSeams.seq_split` /
  :meth:`~ManualSeams.seq_unsplit` are the hand-written transpose pair
  that brings the stream into and out of that layout, keeping every
  weight gradient outside the decoder replicated-consistent across mp.
- **Bucketed / quantized grad reduce** (:mod:`.overlap`,
  :mod:`.quantized`): every gradient that is partial over the data axes
  reduces through the PR 6 buckets — including the stage-sharded
  decoder slabs, whose grads are local to their mp/pp shard and reduce
  over data only. The in-block norm gains (ln1/ln2) see only their
  sequence shard under engaged seams, so their grads additionally psum
  over mp (exact — norms are name-excluded from quantization).
- **ZeRO** (:mod:`.zero`): stage-2 flat chunk-sharded updates and
  stage-3 dim-shard residency with just-in-time slab gathers ride the
  SAME machinery as the pure-data zero mode — the inner
  :class:`~.zero.ZeroPlan` covers the sharding-axis params while the
  mp/pp stage shards update in place on their storage shard (their
  optimizer slots follow the param placements: pipeline/TP sharding of
  the optimizer state falls out for free).
- **Pipeline** (:mod:`..pipeline`): the explicit 1F1B ring and the
  zero-bubble split-backward schedule run INLINE per shard (the stage
  ordinal comes from the region's sharded iota), composing with the
  dp×mp program per stage — the only lowering of a hybrid pipeline this
  XLA accepts.

Escape hatches (all proven byte-for-byte: a declined plan never touches
the program): ``PTPU_QUANT_COLLECTIVES=0`` (master), ``PTPU_COMPOSED=0``
(this mode only), ``PTPU_ZERO_MODE=0`` (stage>=2 meshes fall back to the
GSPMD placement-hint program), ``PTPU_PIPELINE_SCHEDULE=0`` (pp-live
meshes fall back likewise).
"""
from __future__ import annotations

import contextlib
import dataclasses
import enum
import functools
import os

import jax
import jax.numpy as jnp

from ... import telemetry as _telemetry
from ..pipeline import _int_cotangent as _f0
from .overlap import GradReducePlan, partition_buckets, reduce_grads as _bucket_reduce
from .quantized import QUANT_BLOCK
from . import zero as _zero


# ---------------------------------------------------------------------------
# Structured engagement verdicts (satellite: every resolved plan logs ONE
# plan_engagement event so a silently-declined hybrid config is visible
# in tools/telemetry_report.py's -- plans -- section)
# ---------------------------------------------------------------------------
class Reason(str, enum.Enum):
    """Why a plan engaged or declined — the enum IS the telemetry label."""

    ENGAGED = "engaged"
    MASTER_OFF = "master_knob_off"
    COMPOSED_OFF = "composed_knob_off"
    CHECKIFY = "checkify_debug"
    MESH_AXES = "unsupported_mesh_axes"
    NOT_HYBRID = "mesh_not_hybrid"
    NO_DATA_AXIS = "no_data_axis"
    SEAM_FORCED = "tp_seam_forced"
    VOCAB_SHARDED_HEAD = "vocab_sharded_head"
    ZERO3_PLACEMENT = "zero3_data_axis_placement"
    NO_QUANTIZABLE_GRAD = "no_quantizable_grad"
    STAGE_LT_2 = "stage_lt_2"
    ZERO_MODE_OFF = "zero_mode_off"
    OPTIMIZER_STATS = "optimizer_cross_element_stats"
    CLIP_BY_NORM = "clip_grad_by_norm"
    FROZEN_SHARD = "frozen_data_axis_shard"
    RING_OFF = "ring_attn_off"
    NO_SEP = "no_sep_axis"
    ZERO_REQUESTED = "zero_stage_requested"
    SEQ_GATE = "seq_shape_gate"
    NO_SHARDABLE_STATE = "no_shardable_state"
    UNSPECIFIED = "unspecified"
    MODEL_INELIGIBLE = "model_ineligible"
    PIPELINE_OFF = "pipeline_schedule_off"
    INTERLEAVE = "interleave_not_composed"
    LAYERS_INDIVISIBLE = "layers_indivisible_by_pp"
    QUANT_GATE = "quant_parity_gate"
    QUANT_SEAM = "tp_seam_owns_gemm"
    QUANT_FUSED_FFN = "fused_kernel_owns_gemm"
    QUANT_PIPELINE = "pipeline_stage_fn"
    QUANT_COMPOSED = "composed_region"


#: human strings for the enum (the "enum + human string" contract)
REASON_TEXT = {
    Reason.ENGAGED: "plan engaged",
    Reason.MASTER_OFF: "PTPU_QUANT_COLLECTIVES=0 master escape hatch",
    Reason.COMPOSED_OFF: "PTPU_COMPOSED=0 escape hatch",
    Reason.CHECKIFY: "FLAGS_check_nan_inf: checkify cannot instrument "
                     "through a manual region",
    Reason.MESH_AXES: "a live mesh axis outside this plan's lattice row",
    Reason.NOT_HYBRID: "no live mp/pp axis — the pure-data plans own "
                       "this mesh",
    Reason.NO_DATA_AXIS: "ZeRO sharded update needs a live data axis",
    Reason.SEAM_FORCED: "PTPU_TP_SEAM=fused: the island seams own the "
                        "manual region",
    Reason.VOCAB_SHARDED_HEAD: "vocab-sharded CE opens its own mp island",
    Reason.ZERO3_PLACEMENT: "a param is sharded over a data axis under a "
                            "live mp axis (pre-compose rule)",
    Reason.NO_QUANTIZABLE_GRAD: "no gradient large enough to quantize — "
                                "the pre-PR program is kept byte-for-byte",
    Reason.STAGE_LT_2: "sharding stage < 2",
    Reason.ZERO_MODE_OFF: "PTPU_ZERO_MODE=0 escape hatch",
    Reason.OPTIMIZER_STATS: "factored/int8-moment optimizer computes "
                            "cross-element statistics wrong on a shard",
    Reason.CLIP_BY_NORM: "ClipGradByNorm needs full grad tensors",
    Reason.FROZEN_SHARD: "a frozen param carries a data-axis shard",
    Reason.RING_OFF: "PTPU_RING_ATTN=0 escape hatch",
    Reason.NO_SEP: "no live sep axis",
    Reason.ZERO_REQUESTED: "sharding stage >= 2 requested: the ring "
                           "yields the manual region (the zero mode "
                           "itself declines sep-live meshes, so neither "
                           "engages there)",
    Reason.SEQ_GATE: "sequence length fails the shape gate for this "
                     "batch signature",
    Reason.NO_SHARDABLE_STATE: "no parameter is big enough to shard",
    Reason.UNSPECIFIED: "builder declined without a recorded reason "
                        "(e.g. a stubbed-out builder)",
    Reason.MODEL_INELIGIBLE: "model has no composable flagship decoder "
                             "stack",
    Reason.PIPELINE_OFF: "PTPU_PIPELINE_SCHEDULE=0 escape hatch",
    Reason.INTERLEAVE: "interleaved (VPP) storage layout is not "
                       "composable yet",
    Reason.LAYERS_INDIVISIBLE: "num_layers not divisible by pp",
    Reason.QUANT_GATE: "numeric parity probe failed (or CPU default-off) — "
                       "scaled GEMMs stay wide",
    Reason.QUANT_SEAM: "engaged tp seams own the row/col matmul layouts "
                       "(PR 6/7 precedence)",
    Reason.QUANT_FUSED_FFN: "a fused FFN kernel (swiglu_down / _ffn_i8) "
                            "owns these GEMMs",
    Reason.QUANT_PIPELINE: "pipeline stage_fn does not thread amax state",
    Reason.QUANT_COMPOSED: "composed manual region does not thread amax "
                           "state",
}


_PLAN_ENGAGEMENT = _telemetry.counter(
    "plan_engagement_total",
    "plan resolutions at step build, by verdict and structured reason "
    "(docs/COMMS.md lattice; one tick per resolved plan)",
    labelnames=("plan", "verdict", "reason"))

#: newest resolution per plan name (host-side, for bench blocks/tests)
_LAST_VERDICTS = {}


def note_plan_engagement(plan_name, reason):
    """Record one plan resolution: ``reason`` is a :class:`Reason` (or
    raw string); verdict derives from it. Returns the verdict string."""
    reason = Reason(reason) if not isinstance(reason, Reason) else reason
    verdict = "engaged" if reason is Reason.ENGAGED else "declined"
    _LAST_VERDICTS[plan_name] = (verdict, reason.value)
    if _telemetry.get_registry().enabled:
        _PLAN_ENGAGEMENT.inc(labels=(plan_name, verdict, reason.value))
    return verdict


def last_verdicts():
    """{plan: (verdict, reason)} of the newest build's resolutions."""
    return dict(_LAST_VERDICTS)


def note_decline(reason_out, reason):
    """Append a structured decline ``reason`` to a builder's
    ``reason_out`` list (when given) and return None — the shared
    decline idiom of every plan builder."""
    if reason_out is not None:
        reason_out.append(reason)
    return None


#: The compatibility lattice, declaratively: for each mechanism, the
#: mesh-axis rows it engages on and the features it composes with.
#: docs/COMMS.md renders this table; tests/test_compose.py asserts it.
COMPAT_LATTICE = {
    "grad_reduce": {
        "axes": ({"dp"}, {"sharding"}, {"dp", "sharding"}),
        "composes_with": ("quantized", "buckets"),
        "owner_when": "pure-data mesh, stage < 2",
    },
    "zero": {
        "axes": ({"dp"}, {"sharding"}, {"dp", "sharding"}),
        "composes_with": ("quantized", "jit_gather"),
        "owner_when": "pure-data mesh, stage >= 2",
    },
    "ring_attn": {
        "axes": ({"sep"}, {"dp", "sep"}, {"sharding", "sep"},
                 {"dp", "sharding", "sep"}),
        "composes_with": ("grad_reduce", "quantized"),
        "owner_when": "sep live (stage < 2, no mp/pp)",
    },
    "composed": {
        "axes": ({"mp"}, {"pp"}, {"dp", "mp"}, {"dp", "pp"},
                 {"dp", "mp", "pp"}, {"dp", "sharding", "mp"},
                 {"dp", "sharding", "pp"}, {"sharding", "mp"},
                 {"sharding", "pp"}, {"dp", "sharding", "mp", "pp"},
                 {"mp", "pp"}, {"sharding", "mp", "pp"}),
        "composes_with": ("tp_seams", "quantized", "buckets", "zero",
                          "jit_gather", "pipeline_1f1b", "pipeline_zb"),
        "owner_when": "mp and/or pp live (flagship decoder)",
    },
}


def lattice_owner(live_axes, *, stage=0):
    """The :data:`COMPAT_LATTICE` row that OWNS a mesh whose live axes
    are ``live_axes`` (any iterable of axis names), or ``None`` when no
    row accepts the set — the declarative pre-build validity check the
    layout autotuner (memory/autotune.py) consults before paying a
    model build or a trace. Precedence mirrors the build walk:
    composed owns any mp/pp-live mesh, ring owns sep-live pure-data
    meshes (stage < 2 — stage >= 2 with sep live falls off every row,
    exactly the ``owner_when`` annotations), else zero (stage >= 2) /
    grad_reduce. An EMPTY set returns "grad_reduce"/"zero": a degree-1
    mesh is the degenerate pure-data case every plan handles."""
    live = frozenset(live_axes)
    if not live:
        return "zero" if int(stage or 0) >= 2 else "grad_reduce"
    if "mp" in live or "pp" in live:
        return ("composed"
                if live in COMPAT_LATTICE["composed"]["axes"] else None)
    if "sep" in live:
        if int(stage or 0) >= 2:
            return None  # zero declines sep, ring declines stage >= 2
        return ("ring_attn"
                if live in COMPAT_LATTICE["ring_attn"]["axes"] else None)
    row = "zero" if int(stage or 0) >= 2 else "grad_reduce"
    return row if live in COMPAT_LATTICE[row]["axes"] else None


def composed_enabled():
    """``PTPU_COMPOSED`` (default on) on top of the PR 6 master switch —
    ``PTPU_QUANT_COLLECTIVES=0`` must keep every program pre-PR."""
    from . import quant_collectives_enabled

    if not quant_collectives_enabled():
        return False
    return os.environ.get("PTPU_COMPOSED", "1") not in ("0", "off", "false")


def pipeline_schedule_env():
    """``PTPU_PIPELINE_SCHEDULE``: '' (default — the model config's
    ``pp_schedule`` decides), '1f1b'/'zb' (force), '0'/'off'/'false'
    (escape hatch: pp-live meshes keep the pre-PR GSPMD program). Any
    other spelling raises — a mistyped forced knob must not silently
    masquerade as a measured configuration (same contract as
    ``PTPU_FA_BLOCK``)."""
    env = os.environ.get("PTPU_PIPELINE_SCHEDULE", "").strip().lower()
    if env not in ("", "1f1b", "zb", "0", "off", "false"):
        raise ValueError(
            f"PTPU_PIPELINE_SCHEDULE={env!r}: expected '1f1b', 'zb', "
            "'' (model config decides) or '0'/'off'/'false' (escape "
            "hatch, docs/PIPELINE.md)")
    return env


def pipeline_schedule_disabled():
    """True when ``PTPU_PIPELINE_SCHEDULE`` spells the escape hatch —
    the ONE place the accepted off-spellings live (bench.py's
    ``disabled_by_knob`` and the :data:`Reason.PIPELINE_OFF` decline
    both call this, so they can never drift apart)."""
    return pipeline_schedule_env() in ("0", "off", "false")


# ---------------------------------------------------------------------------
# In-region TP seam kernels (per-shard custom_vjp over the manual mp
# axis — the same per-shard math as fused.py's island bodies, minus the
# data-axis weight-grad psum, which the bucketed reduce owns here)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _mm_rs(x, w, axis):
    """Row-parallel seam: x [b, S, k_loc] @ w [k_loc, n] -> partial sums
    resolve directly into sequence shards [b, S/tp, n]."""
    part = x @ w
    return jax.lax.psum_scatter(part, axis, scatter_dimension=1,
                                tiled=True)


def _mm_rs_fwd(x, w, axis):
    return _mm_rs(x, w, axis), (x, w)


def _mm_rs_bwd(axis, res, dy):
    x, w = res
    dyg = jax.lax.all_gather(dy, axis, axis=1, tiled=True)
    dx = (dyg @ w.T).astype(x.dtype)
    dw = jnp.einsum("bsk,bsn->kn", x.astype(jnp.float32),
                    dyg.astype(jnp.float32)).astype(w.dtype)
    return dx, dw


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ag_mm(x, w, axis):
    """Column-parallel seam: seq-sharded x [b, S/tp, h] all-gathers into
    the matmul with the mp-sharded weight -> [b, S, n_loc]."""
    xg = jax.lax.all_gather(x, axis, axis=1, tiled=True)
    return xg @ w


def _ag_mm_fwd(x, w, axis):
    # save the SEQ-SHARDED input and re-gather in backward (the
    # remat-friendly choice, mirroring fused.py)
    return _ag_mm(x, w, axis), (x, w)


def _ag_mm_bwd(axis, res, dy):
    x, w = res
    dxp = dy @ w.T                        # partial over tp
    dx = jax.lax.psum_scatter(dxp, axis, scatter_dimension=1,
                              tiled=True).astype(x.dtype)
    xg = jax.lax.all_gather(x, axis, axis=1, tiled=True)
    dw = jnp.einsum("bsh,bsn->hn", xg.astype(jnp.float32),
                    dy.astype(jnp.float32)).astype(w.dtype)
    return dx, dw


_ag_mm.defvjp(_ag_mm_fwd, _ag_mm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _seq_split(x, ordinal, axis, tp):
    """[b, S, ...] replicated over mp -> this shard's seq chunk. The
    hand-written backward ALL-GATHERS the chunk cotangents, so every
    consumer upstream (embedding) sees the replicated-consistent full
    gradient — mp never enters its reduce axes."""
    chunk = x.shape[1] // tp
    return jax.lax.dynamic_slice_in_dim(x, ordinal * chunk, chunk, 1)


def _seq_split_fwd(x, ordinal, axis, tp):
    return _seq_split(x, ordinal, axis, tp), ordinal


def _seq_split_bwd(axis, tp, ordinal, dy):
    return jax.lax.all_gather(dy, axis, axis=1, tiled=True), _f0(ordinal)


_seq_split.defvjp(_seq_split_fwd, _seq_split_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _seq_unsplit(x, ordinal, axis, tp):
    """Seq-sharded [b, S/tp, ...] -> full [b, S, ...] (replicated across
    mp); backward hands each shard ITS chunk of the cotangent — the
    exact transpose of :func:`_seq_split`."""
    return jax.lax.all_gather(x, axis, axis=1, tiled=True)


def _seq_unsplit_fwd(x, ordinal, axis, tp):
    return _seq_unsplit(x, ordinal, axis, tp), ordinal


def _seq_unsplit_bwd(axis, tp, ordinal, dy):
    chunk = dy.shape[1] // tp
    return (jax.lax.dynamic_slice_in_dim(dy, ordinal * chunk, chunk, 1),
            _f0(ordinal))


_seq_unsplit.defvjp(_seq_unsplit_fwd, _seq_unsplit_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _grad_psum(x, axes):
    """Identity whose backward psums the cotangent over ``axes``. The
    AD-reversed inline 1F1B ring consumes its input only on stage 0, so
    the input cotangent is stage-0-local — the shard_map ISLAND version
    got its psum from the replicated in_spec's transpose, and the
    hand-written zero-bubble backward psums dx itself; this restores
    the same replicated-consistency for the inline AD path."""
    return x


def _grad_psum_fwd(x, axes):
    return x, None


def _grad_psum_bwd(axes, _res, dy):
    return (jax.lax.psum(dy, axes),)


_grad_psum.defvjp(_grad_psum_fwd, _grad_psum_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_keep(x, axes):
    """psum whose backward is the IDENTITY — the closing reduce of the
    inline 1F1B ring. Per-shard AD of a plain psum sums the cotangents
    of every rank's redundant downstream copy (the loss is computed on
    every pp rank from the replicated ring output), over-counting every
    upstream gradient by pp; the true per-rank adjoint of "replicate
    the last stage's buffer" hands each rank its own copy's cotangent."""
    return jax.lax.psum(x, axes)


def _psum_keep_fwd(x, axes):
    return jax.lax.psum(x, axes), None


def _psum_keep_bwd(axes, _res, dy):
    return (dy,)


_psum_keep.defvjp(_psum_keep_fwd, _psum_keep_bwd)


class ManualSeams:
    """Duck-types :class:`~.fused.TPSeamPlan` for ``_block_pure``'s
    ``_row``/``_col`` routing, but runs PER SHARD inside the composed
    manual region (no nested shard_map island). ``calls`` counts seam
    routings so the build can assert the trace actually engaged them."""

    __slots__ = ("axis", "tp", "ordinal", "calls")

    def __init__(self, axis, tp, ordinal):
        self.axis = axis
        self.tp = tp
        self.ordinal = ordinal
        self.calls = 0

    def _check_seq(self, s, what):
        if s % self.tp != 0:
            raise ValueError(
                f"composed tp seams: {what} length {s} does not divide "
                f"by tp={self.tp} — pad the sequence or disable "
                "composition (PTPU_COMPOSED=0, docs/COMMS.md)")

    def matmul_reduce_scatter(self, x, w):
        self.calls += 1
        self._check_seq(x.shape[1], "sequence")
        return _mm_rs(x, w, self.axis)

    def all_gather_matmul(self, x, w):
        self.calls += 1
        return _ag_mm(x, w, self.axis)

    def seq_split(self, x):
        self._check_seq(x.shape[1], "sequence")
        return _seq_split(x, self.ordinal, self.axis, self.tp)

    def seq_unsplit(self, x):
        return _seq_unsplit(x, self.ordinal, self.axis, self.tp)


# ---------------------------------------------------------------------------
# Composed scope: the ShardedTrainStep opens it while tracing its
# per-shard body; StackedDecoder.forward consults it (models/gpt.py) to
# route seams / the inline pipeline. Tracing is single-threaded per
# process (same discipline as collectives.manual_grad_region).
# ---------------------------------------------------------------------------
_COMPOSED_CTX = [None]


@contextlib.contextmanager
def composed_scope(ctx):
    prev = _COMPOSED_CTX[0]
    _COMPOSED_CTX[0] = ctx
    try:
        yield
    finally:
        _COMPOSED_CTX[0] = prev


def active_composed_context():
    return _COMPOSED_CTX[0]


class ComposedContext:
    """Per-trace context: the plan plus this shard's traced ordinals."""

    def __init__(self, plan, tp_ordinal=None, stage_ordinal=None):
        self.plan = plan
        self.stage_id = stage_ordinal
        self.seams = (ManualSeams(plan.tp_axis, plan.tp, tp_ordinal)
                      if plan.tp_seams else None)
        self.decoder_calls = 0

    def pipeline_apply(self, block, x, params, gather=False):
        """Run the decoder stack as the composed pipeline schedule over
        this shard's stage slab (params are the LOCAL [L/pp, ...]
        leaves). 1F1B is the AD-reversed compiled ring; 'zb' is the
        hand-written split-backward schedule (dgrad ring + batched
        wgrad) — both per-shard, stage ordinal from the region iota."""
        from .. import pipeline as _pl

        plan = self.plan
        n_micro = plan.n_micro
        unroll = 2 if gather else 1

        def stage_fn(stage_params, xm):
            def step(c, p):
                return block(c, p), None

            out, _ = jax.lax.scan(step, xm, tuple(stage_params),
                                  unroll=unroll)
            return out

        if plan.pp_schedule != "zb":
            # the AD ring consumes x only on stage 0: psum the input
            # cotangent over pp so upstream (embedding) grads stay
            # replicated-consistent (the zb backward psums dx itself)
            x = _grad_psum(x, (plan.pp_axis,))
        x_mb = _pl.microbatch(x, n_micro)
        if plan.pp_schedule == "zb":
            out = _pl.zero_bubble_schedule(
                stage_fn, tuple(params), x_mb, plan.pp, self.stage_id,
                axis_name=plan.pp_axis)
        else:
            out = _pl.pipeline_schedule(
                lambda xm: stage_fn(tuple(params), xm), x_mb, plan.pp,
                axis_name=plan.pp_axis, stage_id=self.stage_id,
                psum_fn=_psum_keep)
        return _pl.unmicrobatch(out)


# ---------------------------------------------------------------------------
# The composed plan
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class ComposedPlan:
    """Static description of one composed step, resolved at build time
    (knobs at BUILD, never per call). Duck-types the GradReducePlan
    accounting surface (note_grad_reduce / bench "comms") and carries an
    inner :class:`~.zero.ZeroPlan` for the sharding-axis params."""

    axes: tuple                 # ALL region axes (data + mp? + pp?)
    data_axes: tuple
    nranks: int                 # product over data axes (grad-mean divisor)
    tp_axis: str | None = None
    tp: int = 1
    tp_seams: bool = False
    pp_axis: str | None = None
    pp: int = 1
    pp_schedule: str | None = None      # "1f1b" | "zb" | None
    n_micro: int = 1
    zero: object | None = None          # inner ZeroPlan (data axes only)
    reduce_main: object | None = None   # GradReducePlan over data axes
    tp_partial: tuple = ()              # names needing an extra mp psum
    param_specs: dict = dataclasses.field(default_factory=dict)
    sumsq_axes: dict = dataclasses.field(default_factory=dict)
    # stage-1 (shard_opt_states) slot sharding kept THROUGH the region:
    # name -> (dim, degree) for params whose param-shaped optimizer
    # slots stay stored as 1/degree shards over "sharding" — gathered
    # exactly (all_gather) just before the update, sliced back to the
    # shard right after (the stage-3 JIT-gather discipline applied to
    # slots; resident HBM keeps the stage-1 memory win)
    slot_shards: dict = dataclasses.field(default_factory=dict)
    quant_block: int = QUANT_BLOCK

    # -- GradReducePlan-compatible accounting ---------------------------
    @property
    def axis_label(self):
        return "+".join(self.data_axes) if self.data_axes else "-"

    @property
    def buckets(self):
        return self.reduce_main.buckets if self.reduce_main else ()

    @property
    def calls(self):
        n = len(self.buckets) + len(self.tp_partial)
        if self.zero is not None:
            n += self.zero.calls
        return n

    @property
    def exact_bytes(self):
        n = sum(b.payload_bytes for b in self.buckets if not b.quantized)
        if self.zero is not None:
            n += self.zero.exact_bytes
        return n

    @property
    def quantized_payload_bytes(self):
        n = sum(b.payload_bytes for b in self.buckets if b.quantized)
        if self.zero is not None:
            n += self.zero.quantized_payload_bytes
        return n

    @property
    def quantized_wire_bytes(self):
        from .quantized import quantized_wire_bytes as _qw

        n = sum(_qw(b.numel, self.nranks, block=self.quant_block)
                for b in self.buckets if b.quantized)
        if self.zero is not None:
            n += self.zero.quantized_wire_bytes
        return n

    def composed_summary(self):
        return {
            "engaged": True,
            "axes": list(self.axes),
            "data_axes": list(self.data_axes),
            "tp_axis": self.tp_axis, "tp": self.tp,
            "tp_seams": bool(self.tp_seams),
            "pp_axis": self.pp_axis, "pp": self.pp,
            "pp_schedule": self.pp_schedule,
            "n_micro": self.n_micro,
            "zero_stage": (self.zero.stage if self.zero is not None
                           else 0),
            "stage1_slot_shards": len(self.slot_shards),
            "buckets": len(self.buckets),
            "tp_partial": list(self.tp_partial),
        }

    def summary(self):
        """GradReducePlan-shaped comms summary + the composed lattice
        row (+ the inner zero block when engaged)."""
        qp = self.quantized_payload_bytes
        eb = self.exact_bytes
        out = {
            "axes": list(self.data_axes), "nranks": self.nranks,
            "buckets": self.calls,
            "quantized_buckets":
                sum(1 for b in self.buckets if b.quantized)
                + (sum(1 for p in self.zero.params if p.quantized)
                   if self.zero is not None else 0),
            "exact_bytes": int(eb),
            "quantized_payload_bytes": int(qp),
            "quantized_wire_bytes": int(self.quantized_wire_bytes),
            "quantized_fraction": (float(qp) / float(eb + qp)
                                   if (eb + qp) else 0.0),
            "composed": self.composed_summary(),
        }
        if self.zero is not None:
            out["zero"] = self.zero.zero_summary()
        return out

    def zero_summary(self):
        if self.zero is not None:
            return self.zero.zero_summary()
        return {"stage": 0, "engaged": False}


def _region_spec(t, region_axes):
    """Storage PartitionSpec of a tensor inside the region: placements
    filtered to live region axes (dead axes partition nothing)."""
    from jax.sharding import PartitionSpec as P

    from ..auto_parallel import Shard

    da = getattr(t, "_dist_attr", None)
    if da is None:
        return P()
    by_dim = {}
    for ax_name, pl in zip(da.process_mesh.dim_names, da.placements):
        if (isinstance(pl, Shard) and ax_name in region_axes):
            by_dim.setdefault(pl.dim, []).append(ax_name)
    if not by_dim:
        return P()
    entries = []
    for d in range(max(by_dim) + 1):
        axes = by_dim.get(d, [])
        entries.append(None if not axes
                       else (axes[0] if len(axes) == 1 else tuple(axes)))
    return P(*entries)


def _local_shape(shape, spec, sizes):
    """Per-shard shape of a tensor stored with ``spec`` on the region."""
    out = list(shape)
    for d, e in enumerate(spec or ()):
        if e is None:
            continue
        for ax in (e if isinstance(e, tuple) else (e,)):
            out[d] //= sizes[ax]
    return tuple(out)


def _find_decoder(model):
    from ...models.gpt import StackedDecoder

    hits = [(prefix, l) for prefix, l in
            model.named_sublayers(include_self=True)
            if isinstance(l, StackedDecoder)]
    return hits[0] if len(hits) == 1 else (None, None)


def stage1_slot_dim(shape, size):
    """The dim a stage-1 (``shard_opt_states``) param-shaped optimizer
    slot shards over: the FIRST dim divisible by the sharding degree —
    ONE resolver shared by ``ShardedTrainStep._slot_sharding`` (storage
    placement) and the composed plan (region in/out specs), so the two
    can never disagree about the layout. None = not shardable."""
    for d, n in enumerate(shape):
        if n and n % size == 0:
            return d
    return None


def stage1_slot_spec(param_spec, dim):
    """``param_spec`` with the "sharding" axis appended at ``dim`` —
    the storage PartitionSpec of a stage-1 sharded slot whose param is
    stored with ``param_spec`` (mp/pp slabs keep their placements)."""
    from jax.sharding import PartitionSpec as P

    entries = list(param_spec) + [None] * (dim + 1 - len(param_spec))
    cur = entries[dim]
    if cur is None:
        entries[dim] = "sharding"
    else:
        cur = tuple(cur) if isinstance(cur, tuple) else (cur,)
        entries[dim] = cur + ("sharding",)
    return P(*entries)


def build_composed_plan(model, optimizer, mesh, *, sharding_stage=None,
                        shard_vocab_head=None, grad_clip=None,
                        shard_opt_states=False):
    """Resolve the composed hybrid plan, or ``(None, Reason)``.

    Returns ``(ComposedPlan | None, Reason)`` — the reason is
    :data:`Reason.ENGAGED` on success, else the first lattice row the
    config fell off. Callers record it via
    :func:`note_plan_engagement`."""
    from ...core.tensor import Parameter
    from ..auto_parallel import Shard
    from ...models.gpt import StackedDecoder, _BLOCK_PARAM_FIELDS
    from . import grads_quantized
    from .fused import tp_seam_mode

    if not composed_enabled():
        from . import quant_collectives_enabled

        return None, (Reason.MASTER_OFF if not quant_collectives_enabled()
                      else Reason.COMPOSED_OFF)
    live = {a: mesh.get_dim_size(a) for a in mesh.dim_names
            if mesh.get_dim_size(a) > 1}
    if not (live.get("mp", 1) > 1 or live.get("pp", 1) > 1):
        return None, Reason.NOT_HYBRID
    if not set(live) <= {"dp", "sharding", "mp", "pp"}:
        return None, Reason.MESH_AXES
    from ...utils.flags import get_flags

    if get_flags("check_nan_inf")["check_nan_inf"]:
        return None, Reason.CHECKIFY
    mp_live = live.get("mp", 1) > 1
    if (shard_vocab_head and shard_vocab_head in mesh.dim_names
            and mesh.get_dim_size(shard_vocab_head) > 1):
        return None, Reason.VOCAB_SHARDED_HEAD
    if tp_seam_mode() == "fused" and mp_live:
        # explicit island forcing: the PR 6 seam islands own the program
        return None, Reason.SEAM_FORCED
    prefix, decoder = _find_decoder(model)
    if decoder is None:
        return None, Reason.MODEL_INELIGIBLE
    cfg = decoder.config
    data_axes = tuple(a for a in ("dp", "sharding") if a in live)
    region_axes = data_axes + tuple(
        a for a in ("mp", "pp") if a in live)
    sizes = dict(live)

    slab_names = {(prefix + "." if prefix else "") + attr: attr
                  for attr, _ in _BLOCK_PARAM_FIELDS}
    tp_dims = StackedDecoder._TP_DIMS

    # -- pipeline row ---------------------------------------------------
    pp_axis, pp, pp_schedule, n_micro = None, 1, None, 1
    staged = False
    if live.get("pp", 1) > 1:
        pp = live["pp"]
        # stage placements must actually shard the slabs (Shard(0) over
        # pp); without them the decoder is replicated over pp and the
        # pre-PR GSPMD program handles the mesh unchanged
        da = getattr(decoder.wq, "_dist_attr", None)
        staged = da is not None and any(
            isinstance(pl, Shard) and pl.dim == 0 and ax == "pp"
            for ax, pl in zip(da.process_mesh.dim_names, da.placements))
        if staged:
            env = pipeline_schedule_env()
            if pipeline_schedule_disabled():
                return None, Reason.PIPELINE_OFF
            if (getattr(cfg, "pp_interleave", 1) or 1) > 1:
                return None, Reason.INTERLEAVE
            if cfg.num_layers % pp != 0:
                return None, Reason.LAYERS_INDIVISIBLE
            pp_axis = "pp"
            pp_schedule = env if env in ("1f1b", "zb") else (
                getattr(cfg, "pp_schedule", "1f1b") or "1f1b")
            n_micro = getattr(cfg, "pp_microbatches", None) or pp

    # -- tp row ---------------------------------------------------------
    tp_axis, tp, tp_seams = None, 1, False
    if mp_live:
        tp_axis, tp = "mp", live["mp"]
        da = getattr(decoder.wq, "_dist_attr", None)
        if da is not None:
            tp_seams = any(
                isinstance(pl, Shard) and pl.dim > 0 and ax == "mp"
                for ax, pl in zip(da.process_mesh.dim_names,
                                  da.placements))

    # composition must ADD something the per-plan paths cannot do: tp
    # seams and/or a staged pipeline. An mp/pp axis that no placement
    # uses is dead weight the pre-PR program already handles (the dp
    # grad-reduce plan engages over the data axes as before).
    if not (tp_seams or staged):
        return None, Reason.NOT_HYBRID

    # -- param walk: eligibility + zero classification ------------------
    stage = _zero.resolve_stage(optimizer, sharding_stage)
    zero_wanted = stage >= 2
    if zero_wanted and not _zero.zero_mode_enabled():
        return None, Reason.ZERO_MODE_OFF
    if zero_wanted and optimizer is not None and (
            getattr(optimizer, "_factored", False)
            or getattr(optimizer, "_moment_dtype", None)):
        return None, Reason.OPTIMIZER_STATS
    # per-tensor norm clip needs FULL grad tensors, but the composed
    # update tail runs per shard on mp/pp slab slices at EVERY stage
    # (global-norm clip psums its sumsq via gsumsq_fn; per-tensor clip
    # has no such channel — a local-slice norm silently diverges)
    from ...nn.clip import ClipGradByNorm

    if isinstance(grad_clip, ClipGradByNorm):
        return None, Reason.CLIP_BY_NORM
    shard_axis = None
    if zero_wanted:
        shard_axis = ("sharding" if "sharding" in live
                      else ("dp" if "dp" in live else None))
        if shard_axis is None:
            return None, Reason.NO_DATA_AXIS

    entries = model.state_dict()
    named = [(n, t) for n, t in entries.items()
             if isinstance(t, Parameter)]
    quant = grads_quantized()
    jit_gather = _zero.jit_gather_enabled()
    zero_params = []
    bucket_named = []          # (name, LOCAL shape, dtype) for the buckets
    tp_partial = []
    param_specs = {}
    sumsq_axes = {}
    degree = live.get(shard_axis, 1) if shard_axis else 1
    for name, t in named:
        arr = t._data
        shape = tuple(int(d) for d in arr.shape)
        dtype = str(jnp.dtype(arr.dtype))
        spec = _region_spec(t, region_axes)
        da = getattr(t, "_dist_attr", None)
        sdim = None
        stage_axes = []
        if da is not None:
            for ax_name, pl in zip(da.process_mesh.dim_names,
                                   da.placements):
                if not isinstance(pl, Shard):
                    continue
                if live.get(ax_name, 1) <= 1:
                    continue          # dead-axis marks partition nothing
                if ax_name == shard_axis:
                    sdim = pl.dim
                elif ax_name in ("mp", "pp"):
                    # only the staged decoder slabs are handled
                    # in-region (an mp shard must also sit on a tp
                    # dim): anything else would swap its LOCAL slice
                    # in as the full tensor — silently wrong numerics
                    if name not in slab_names or (
                            ax_name == "mp"
                            and slab_names[name] not in tp_dims):
                        return None, Reason.MODEL_INELIGIBLE
                    stage_axes.append(ax_name)
                else:
                    return None, Reason.MESH_AXES
        if not t.trainable:
            # any live-axis shard (data OR mp/pp): a frozen shard would
            # ride the region as a replicated buffer while the seam /
            # stage kernels expect a local slice — wrong numerics
            if sdim is not None or stage_axes:
                return None, Reason.FROZEN_SHARD
            continue
        param_specs[name] = spec
        is_slab = name in slab_names
        # in-block norm gains see only their seq shard under engaged
        # seams: their grads are PARTIAL over mp (exact psum — norms are
        # name-excluded from quantization)
        partial_mp = (tp_seams and is_slab
                      and slab_names[name] not in tp_dims)
        if partial_mp:
            tp_partial.append(name)
        numel = 1
        for d in shape:
            numel *= d
        if sdim is not None:
            if stage < 3:
                return None, Reason.ZERO3_PLACEMENT
            attr = slab_names.get(name)
            zero_params.append(_zero.ZeroParam(
                name, "dim", shape, dtype, numel, shard_dim=sdim,
                deferred_attr=(attr if (attr and sdim >= 1 and jit_gather)
                               else None),
                spec=spec))
            sumsq_axes[name] = tuple(
                [shard_axis] + stage_axes
                if not partial_mp else
                [a for a in [shard_axis] + stage_axes if a != "mp"])
        elif (zero_wanted and not stage_axes and numel >= degree
              and shape and jnp.issubdtype(jnp.dtype(dtype), jnp.inexact)):
            q = quant and not _zero.is_exact_grad(name, shape, dtype)
            zero_params.append(_zero.ZeroParam(
                name, "flat", shape, dtype, numel, quantized=q,
                padded=_zero.flat_padded_len(numel, degree, quantized=q)))
            sumsq_axes[name] = (shard_axis,)
        else:
            lshape = _local_shape(shape, spec, sizes)
            bucket_named.append((name, lshape, dtype))
            sumsq_axes[name] = tuple(stage_axes)
    # a dim-sharded slab whose gather rides mp-partial grads: the dim
    # kind's sumsq psums over shard_axis (+pp); mp was already summed by
    # the pre-reduce psum, so exclude it above.

    # one data-rank product: the ZeroPlan and ComposedPlan nranks are
    # both the grad-mean divisor and must stay equal
    nranks = 1
    for a in data_axes:
        nranks *= live[a]

    zplan = None
    if zero_wanted and any(p.kind in ("dim", "flat") for p in zero_params):
        zplan = _zero.ZeroPlan(
            stage=stage, axes=data_axes, shard_axis=shard_axis,
            shard_degree=degree, nranks=nranks,
            params=tuple(zero_params),
            gather_quantized=_zero.param_gather_quantized())

    # -- stage-1 slot sharding (ROADMAP item 2 follow-up (c)) -----------
    # shard_opt_states keeps its dp-sharded slot layout THROUGH the
    # composed region: the region's slot in/out specs carry the
    # storage's "sharding" extension, the update gathers the shard
    # exactly and slices the result back (stage1_gather_slots /
    # stage1_slice_slots) — resident slot HBM stays 1/degree instead of
    # resharding to replicated at the region boundary. Stage >= 2 slots
    # are owned by the inner ZeroPlan and skip this walk.
    slot_shards = {}
    if shard_opt_states and not zero_wanted and live.get("sharding", 1) > 1:
        ssize = live["sharding"]
        for name, t in named:
            if not t.trainable or name not in param_specs:
                continue
            shape = tuple(int(d) for d in t._data.shape)
            d = stage1_slot_dim(shape, ssize)
            if d is None:
                continue
            # the region view divides dims by their mp/pp placements
            # too: only engage when the LOCAL dim still divides evenly
            # (otherwise the slot keeps today's replicated region ride)
            lshape = _local_shape(shape, param_specs[name], sizes)
            if lshape[d] % ssize:
                continue
            slot_shards[name] = (d, ssize)
        note_plan_engagement(
            "zero_stage1",
            Reason.ENGAGED if slot_shards else Reason.NO_SHARDABLE_STATE)
    reduce_main = None
    main_named = [e for e in bucket_named if e[0] not in tp_partial]
    if data_axes and main_named:
        buckets = partition_buckets(main_named, quantized=quant)
        reduce_main = GradReducePlan(axes=data_axes, nranks=nranks,
                                     buckets=buckets)
    return ComposedPlan(
        axes=region_axes, data_axes=data_axes, nranks=max(nranks, 1),
        tp_axis=tp_axis, tp=tp, tp_seams=tp_seams,
        pp_axis=pp_axis, pp=pp, pp_schedule=pp_schedule, n_micro=n_micro,
        zero=zplan, reduce_main=reduce_main,
        tp_partial=tuple(tp_partial), param_specs=param_specs,
        sumsq_axes=sumsq_axes, slot_shards=slot_shards), Reason.ENGAGED


# ---------------------------------------------------------------------------
# Per-shard reduce / update / restore helpers (called inside the region)
# ---------------------------------------------------------------------------
def reduce_grads(grads, plan, zero_ordinal):
    """The composed gradient reduce: zero-kind params through the inner
    ZeroPlan recipes (reduce-scatter / chunk slice), everything else
    through the PR 6 buckets over the data axes; mp-partial norm gains
    psum over mp first (exact)."""
    out = dict(grads)
    tp_ax = (plan.tp_axis,) if plan.tp_axis else ()
    if plan.zero is not None:
        for zp in plan.zero.params:
            g = out.get(zp.name)
            if g is None:
                continue
            if zp.name in plan.tp_partial and tp_ax:
                g = jax.lax.psum(g, tp_ax)
            out[zp.name] = _zero.reduce_grad(g, zp, plan.zero,
                                             zero_ordinal, mean=True)
    if plan.reduce_main is not None:
        out = _bucket_reduce(out, plan.reduce_main, mean=True)
    # mp-partial names outside the zero plan: exact psum over data+mp,
    # mean over the DATA ranks only (the mp terms are partials of one
    # gradient, not copies)
    zcover = set(plan.zero.by_name) if plan.zero is not None else set()
    inv = 1.0 / plan.nranks
    for name in plan.tp_partial:
        g = grads.get(name)
        if g is None or name in zcover:
            continue
        red = jax.lax.psum(g, tuple(plan.data_axes) + tp_ax)
        out[name] = _zero._mean_scale(red, inv, plan.nranks)
    return out


def update_view(params, plan, zero_ordinal):
    out = dict(params)
    if plan.zero is not None:
        sub = {p.name: params[p.name] for p in plan.zero.params}
        out.update(_zero.update_view(sub, plan.zero, zero_ordinal))
    return out


def stage1_gather_slots(opt_state, params, plan):
    """Stage-1 sharded slots -> their full (per-mp/pp-slab) update view:
    one exact tiled all_gather over "sharding" per slot leaf, issued
    just before the update — resident storage stays 1/degree, the
    update math is bit-identical to the replicated layout's."""
    if not plan.slot_shards:
        return opt_state
    out = {}
    for n, slots in opt_state.items():
        sd = plan.slot_shards.get(n)
        p = params.get(n)
        if sd is None or p is None:
            out[n] = slots
            continue
        d, deg = sd
        exp = list(p.shape)
        exp[d] //= deg
        exp = tuple(exp)
        out[n] = {k: (_zero.gather_shard(v, "sharding", d)
                      if tuple(v.shape) == exp else v)
                  for k, v in slots.items()}
    return out


def stage1_slice_slots(new_opt_state, params, plan, ordinal):
    """Updated full slots back to this rank's stage-1 storage shard
    (the gather's exact inverse: a dynamic slice at the shard dim)."""
    if not plan.slot_shards:
        return new_opt_state
    out = {}
    for n, slots in new_opt_state.items():
        sd = plan.slot_shards.get(n)
        p = params.get(n)
        if sd is None or p is None:
            out[n] = slots
            continue
        d, deg = sd
        pshape = tuple(p.shape)
        chunk = pshape[d] // deg
        out[n] = {k: (jax.lax.dynamic_slice_in_dim(
                          v, ordinal * chunk, chunk, axis=d)
                      if tuple(v.shape) == pshape else v)
                  for k, v in slots.items()}
    return out


def params_out(new_upd, plan):
    out = dict(new_upd)
    if plan.zero is not None:
        sub = {p.name: new_upd[p.name] for p in plan.zero.params}
        out.update(_zero.params_out(sub, plan.zero))
    return out


def global_grad_sumsq(grads, plan):
    """f32 sum of squares over the mixed-layout composed grad tree:
    leaves partitioned over some axes in their UPDATE layout psum their
    local sums over exactly those axes; replicated leaves count once."""
    groups = {}
    for name, g in grads.items():
        if g is None:
            continue
        axes = tuple(sorted(plan.sumsq_axes.get(name, ())))
        groups.setdefault(axes, []).append(
            jnp.sum(jnp.square(g.astype(jnp.float32))))
    total = jnp.zeros((), jnp.float32)
    for axes, sums in groups.items():
        s = sum(sums)
        if axes:
            s = jax.lax.psum(s, axes)
        total = total + s
    return total
