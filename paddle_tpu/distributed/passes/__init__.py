"""paddle.distributed.passes (parity: passes/pass_base.py new_pass /
PassManager). On TPU the heavy passes (fusion, scheduling, comm
optimization) belong to XLA; the registry remains for USER program
passes over the recorded static Program (each pass is a callable
Program -> Program)."""
from __future__ import annotations

__all__ = ["new_pass", "PassManager", "PassContext"]

_PASSES = {}


def register_pass(name):
    def deco(fn):
        _PASSES[name] = fn
        return fn

    return deco


class PassContext:
    def __init__(self):
        self.attrs = {}

    def set_attr(self, k, v):
        self.attrs[k] = v

    def get_attr(self, k, default=None):
        return self.attrs.get(k, default)


class _Pass:
    def __init__(self, name, fn, attrs):
        self.name = name
        self._fn = fn
        self._attrs = dict(attrs or {})

    def apply(self, main_programs, startup_programs=None, context=None):
        progs = (main_programs if isinstance(main_programs, (list, tuple))
                 else [main_programs])
        for p in progs:
            self._fn(p, context or PassContext(), **self._attrs)
        return progs


def _xla_owned(program, context, **attrs):
    # fusion/memory/comm passes: XLA applies these during compilation of
    # the replayed program; recording the request is the honest action
    context.set_attr("delegated_to_xla", True)


def new_pass(name, pass_attrs=None):
    fn = _PASSES.get(name, _xla_owned)
    return _Pass(name, fn, pass_attrs)


class PassManager:
    def __init__(self, passes=()):
        self._passes = list(passes)

    def append(self, p):
        self._passes.append(p)

    def apply(self, main_programs, startup_programs=None):
        ctx = PassContext()
        for p in self._passes:
            p.apply(main_programs, startup_programs, ctx)
        return main_programs, startup_programs
