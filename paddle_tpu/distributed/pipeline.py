"""Compiled SPMD pipeline parallelism (the "pp" mesh axis).

Capability parity: the reference's pipeline runtimes — 1F1B
(`fleet/meta_parallel/pipeline_parallel.py:242`), interleave (:1308), and
the static zero-bubble schedule pass — are host-driven microbatch loops
over NCCL p2p. The TPU-native redesign compiles the ENTIRE schedule into
one SPMD program: every stage holds its layer shard (leading-axis sharding
over "pp"), activations rotate between neighbour chips with
``lax.ppermute`` (one ICI hop), and the fill/steady/drain phases are a
``lax.scan`` over ticks. XLA overlaps the ppermute transfer of tick t with
the stage compute of tick t+1 — the same overlap 1F1B gets from separate
comm streams, without the host scheduler, watchdogs, or p2p machinery.

Mapped only over "pp" (partial shard_map): dp/mp/sep shardings inside the
stage function remain visible to GSPMD and compose unchanged.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

P = PartitionSpec


def pipeline_schedule(stage_fn, x_mb, n_stages, axis_name="pp"):
    """Run inside shard_map over `axis_name`.

    stage_fn: activation -> activation (this device's layer shard applied).
    x_mb: [n_micro, ...] microbatched stage-0 input (replicated over pp).
    Returns [n_micro, ...] last-stage outputs, replicated over pp.

    Schedule: n_micro + n_stages - 1 ticks. Tick t: stage 0 ingests
    microbatch t, stage s processes the activation that entered at tick
    t - s, the last stage emits microbatch t - (n_stages - 1).
    """
    idx = jax.lax.axis_index(axis_name)
    n_micro = x_mb.shape[0]
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    out_aval = jax.eval_shape(
        lambda x: stage_fn(jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype),
    )
    state0 = jax.lax.pcast(
        jnp.zeros(out_aval.shape, out_aval.dtype), axis_name, to="varying"
    )
    out_buf0 = jax.lax.pcast(
        jnp.zeros((n_micro,) + tuple(out_aval.shape), out_aval.dtype),
        axis_name, to="varying",
    )

    def tick(carry, t):
        state, out_buf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(idx == 0, x_in, state)
        out = stage_fn(inp)
        o_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = (t >= n_stages - 1) & (idx == n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(out_buf, o_idx, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, out, cur), o_idx, 0
        )
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, out_buf), None

    (state, out_buf), _ = jax.lax.scan(tick, (state0, out_buf0), jnp.arange(total))
    return jax.lax.psum(
        jnp.where(idx == n_stages - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name,
    )


def spmd_pipeline(stage_fn, mesh, n_stages, axis_name="pp",
                  params_spec=None, remat=False):
    """Build the jittable pipelined function over a mesh.

    stage_fn(stage_params, x) -> x, where stage_params is this stage's
    slice of leading-axis-stacked parameters.

    Returns pipelined(stacked_params, x_mb): stacked_params leading axis is
    sharded over `axis_name`; x_mb is [n_micro, ...] microbatches. Output
    is the last stage's [n_micro, ...], replicated over `axis_name`.
    """
    if params_spec is None:
        params_spec = P(axis_name)

    inner = stage_fn
    if remat:
        inner = jax.checkpoint(stage_fn)

    def body(stacked_local, x_mb):
        def one_stage(x):
            return inner(stacked_local, x)

        return pipeline_schedule(one_stage, x_mb, n_stages, axis_name)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        axis_names={axis_name},
    )


def schedule_ticks(n_micro, n_stages):
    """Tick count of the plain schedule (each tick = FULL per-device stage)."""
    return n_micro + n_stages - 1


def interleaved_ticks(n_micro, pp, v):
    """Tick count of the circular/interleaved schedule (each tick = 1/v of a
    device's layers). Normalised bubble: (pp-1)/v small-ticks vs (pp-1) full
    ticks for the plain schedule — the VPP win
    (reference: pipeline_parallel.py:1308 PipelineParallelWithInterleave)."""
    return v * n_micro + pp - 1


def interleaved_pipeline_schedule(stage_fn, x_mb, pp, v, axis_name="pp"):
    """Circular (virtual-stage / VPP) schedule, run inside shard_map.

    Device s holds v chunks; chunk c acts as virtual stage c*pp + s. A
    microbatch makes v laps of the ring; lap l of microbatch m runs on
    device s at tick l*n_micro + m + s. Wrap-around activations (device
    pp-1 -> 0) wait n_micro - pp ticks in a rolling FIFO, so n_micro >= pp
    is required.

    stage_fn(chunk_idx, x) -> x (applies this device's chunk `chunk_idx`).
    x_mb: [n_micro, ...] stage-0 inputs (replicated over pp).
    """
    idx = jax.lax.axis_index(axis_name)
    n_micro = x_mb.shape[0]
    if n_micro < pp:
        raise ValueError(
            f"interleaved schedule needs n_micro >= pp ({n_micro} < {pp})")
    total = interleaved_ticks(n_micro, pp, v)
    wait = n_micro - pp
    fifo_len = wait + 1
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    out_aval = jax.eval_shape(
        lambda x: stage_fn(jnp.zeros((), jnp.int32),
                           jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype),
    )

    def _z(shape):
        return jax.lax.pcast(
            jnp.zeros(shape, out_aval.dtype), axis_name, to="varying")

    state0 = _z(out_aval.shape)
    fifo0 = _z((fifo_len,) + tuple(out_aval.shape))
    out_buf0 = _z((n_micro,) + tuple(out_aval.shape))

    def tick(carry, t):
        fifo, state, out_buf = carry
        # incoming rotated activation -> FIFO slot t%len; device 0 pops the
        # one written `wait` ticks ago (lap wrap), others pop the newest
        w = jnp.mod(t, fifo_len)
        fifo = jax.lax.dynamic_update_index_in_dim(fifo, state, w, 0)
        r = jnp.where(idx == 0, jnp.mod(t - wait + fifo_len, fifo_len), w)
        queued = jax.lax.dynamic_index_in_dim(fifo, r, 0, keepdims=False)

        mb_new = jnp.clip(t, 0, n_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x_mb, mb_new, 0, keepdims=False)
        inp = jnp.where((idx == 0) & (t < n_micro), fresh, queued)

        rel = t - idx  # ticks since this device's first real work
        lap = jnp.clip((rel + v * n_micro) // n_micro - v, 0, v - 1)
        out = stage_fn(lap, inp)

        m = jnp.mod(rel + v * n_micro, n_micro)
        valid = ((idx == pp - 1) & (rel >= (v - 1) * n_micro)
                 & (rel < v * n_micro))
        cur = jax.lax.dynamic_index_in_dim(out_buf, m, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, out, cur), m, 0)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (fifo, state, out_buf), None

    (_, _, out_buf), _ = jax.lax.scan(
        tick, (fifo0, state0, out_buf0), jnp.arange(total))
    return jax.lax.psum(
        jnp.where(idx == pp - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name,
    )


def spmd_pipeline_interleaved(stage_fn, mesh, pp, v, axis_name="pp",
                              remat=False):
    """Jittable interleaved pipeline over leading-axis-stacked params.

    stage_fn(chunk_params, x) -> x where chunk_params is one chunk's slice
    [n_layers/(pp*v), ...] of each stacked param. The caller passes params
    stacked [L, ...] with L % (pp*v) == 0; virtual stage j gets layers
    [j*g, (j+1)*g), g = L/(pp*v), and device s holds chunks {c*pp+s}.
    """
    inner = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(stacked_local, x_mb):
        # local leaves arrive as [v, 1, g, ...] (axis 1 = this device's shard)
        local = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0],) + tuple(a.shape[2:])),
            stacked_local)

        def one_stage(lap, x):
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, lap, 0, keepdims=False),
                local)
            return inner(chunk, x)

        return interleaved_pipeline_schedule(one_stage, x_mb, pp, v,
                                             axis_name)

    def pipelined(stacked_params, x_mb):
        def split(a):
            L = a.shape[0]
            g = L // (pp * v)
            # [L, ...] -> [v, pp, g, ...]: layer j = (c*pp+s)*g + i lands at
            # [c, s, i] — device s's chunk c is virtual stage c*pp+s
            return a.reshape((v, pp, g) + tuple(a.shape[1:]))

        stacked = jax.tree_util.tree_map(split, stacked_params)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(None, axis_name), P()),
            out_specs=P(),
            axis_names={axis_name},
        )(stacked, x_mb)

    return pipelined


def microbatch(batch, n_micro, axis=0):
    """[B, ...] -> [n_micro, B/n_micro, ...]"""
    def _one(x):
        if x.ndim == 0:
            return x
        b = x.shape[axis]
        if b % n_micro != 0:
            raise ValueError(f"batch dim {b} not divisible by {n_micro} microbatches")
        return x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))

    return jax.tree_util.tree_map(_one, batch)


def unmicrobatch(mb):
    def _one(x):
        return x.reshape((-1,) + tuple(x.shape[2:]))

    return jax.tree_util.tree_map(_one, mb)
