"""Compiled SPMD pipeline parallelism (the "pp" mesh axis).

Capability parity: the reference's pipeline runtimes — 1F1B
(`fleet/meta_parallel/pipeline_parallel.py:242`), interleave (:1308), and
the static zero-bubble schedule pass — are host-driven microbatch loops
over NCCL p2p. The TPU-native redesign compiles the ENTIRE schedule into
one SPMD program: every stage holds its layer shard (leading-axis sharding
over "pp"), activations rotate between neighbour chips with
``lax.ppermute`` (one ICI hop), and the fill/steady/drain phases are a
``lax.scan`` over ticks. XLA overlaps the ppermute transfer of tick t with
the stage compute of tick t+1 — the same overlap 1F1B gets from separate
comm streams, without the host scheduler, watchdogs, or p2p machinery.

Mapped only over "pp" (partial shard_map): dp/mp/sep shardings inside the
stage function remain visible to GSPMD and compose unchanged.

Stage ordinals: every schedule body needs "which stage am I" — but
``lax.axis_index`` lowers to the PartitionId HLO, which this container's
XLA rejects under SPMD partitioning (the pre-existing pipeline failure
class). The ordinal therefore rides IN as a ``P(axis_name)``-sharded
iota (the fused-CE / ring-attention trick): the ``spmd_*`` wrappers
thread ``jnp.arange(pp)`` through their shard_map with in_spec
``P(axis_name)`` and the bodies read ``ids[0]``. The schedule functions
accept ``stage_id=`` directly so a caller already inside a manual
region (the composed hybrid step, collectives/compose.py) can pass the
ordinal it holds; ``stage_id=None`` falls back to ``lax.axis_index``
for runtimes whose partitioner lowers it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

P = PartitionSpec


def _stage_ordinal(stage_id, axis_name):
    if stage_id is not None:
        return stage_id
    return jax.lax.axis_index(axis_name)


def _stage_iota(n):
    """The ordinal operand the spmd_* wrappers thread: shard r of a
    P(axis)-sharded arange holds [r]."""
    return jnp.arange(n, dtype=jnp.int32)


def pipeline_schedule_hetero(stage_fn2, x_mb, n_stages, mid_aval, out_aval,
                             axis_name="pp", out_consume=None,
                             stage_id=None, psum_fn=None):
    """The generalised compiled ring, run inside shard_map over
    `axis_name`: stage 0's input type and the LAST stage's output type may
    differ from the rotating carry.

    stage_fn2(x_in, state) -> (mid, final): consumes the raw microbatch
    on stage 0 and the rotated carry elsewhere (the callee selects — with
    a lax.switch over stages, branch 0 simply uses x_in); returns the
    carry to rotate (`mid`, aval `mid_aval`) and the final output
    (`final`, aval `out_aval`, real only on the last stage).

    ``out_consume(final, mb_idx) -> small array``: the last-stage-owned
    output consumer (VERDICT r3 missing-item 6). Without it, the closing
    psum replicates the full per-microbatch output buffer — for a
    vocab-sized head output, (pp-1)/pp of that traffic is zeros
    (reference contrast: stages OWN their outputs,
    fleet/meta_parallel/parallel_layers/pp_layers.py:258). With it, the
    consumer (e.g. the per-microbatch LM loss) runs IN-RING on the owner
    stage and only its small result crosses the ring: the vocab-sized
    buffer never moves. Returns [n_micro, *small] instead of
    [n_micro, *out_aval].

    Schedule: n_micro + n_stages - 1 ticks. Tick t: stage 0 ingests
    microbatch t, stage s processes the activation that entered at tick
    t - s, the last stage emits microbatch t - (n_stages - 1).
    """
    idx = _stage_ordinal(stage_id, axis_name)
    n_micro = x_mb.shape[0]
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def _z(aval, extra=()):
        return jax.lax.pcast(
            jnp.zeros(tuple(extra) + tuple(aval.shape), aval.dtype),
            axis_name, to="varying")

    state0 = _z(mid_aval)
    if out_consume is None:
        buf_aval = out_aval
    else:
        buf_aval = jax.eval_shape(
            out_consume,
            jax.ShapeDtypeStruct(tuple(out_aval.shape), out_aval.dtype),
            jax.ShapeDtypeStruct((), jnp.int32))
    out_buf0 = _z(buf_aval, (n_micro,))

    def tick(carry, t):
        state, out_buf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        mid, fin = stage_fn2(x_in, state)
        o_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        if out_consume is not None:
            fin = out_consume(fin, o_idx)
        valid = (t >= n_stages - 1) & (idx == n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(out_buf, o_idx, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, fin, cur), o_idx, 0
        )
        state = jax.lax.ppermute(mid, axis_name, perm)
        return (state, out_buf), None

    (state, out_buf), _ = jax.lax.scan(tick, (state0, out_buf0), jnp.arange(total))
    # ``psum_fn`` hook: a caller differentiating PER SHARD inside an
    # already-manual region (collectives/compose) passes a psum whose
    # transpose is the identity — the default ``lax.psum`` transpose
    # sums the cotangents of every rank's REDUNDANT downstream copy,
    # over-counting upstream grads by n_stages.
    closing = psum_fn or jax.lax.psum
    return closing(
        jnp.where(idx == n_stages - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name,
    )


def pipeline_schedule(stage_fn, x_mb, n_stages, axis_name="pp",
                      stage_id=None, psum_fn=None):
    """Uniform-aval ring (stage_fn: activation -> activation) — a thin
    wrapper over `pipeline_schedule_hetero` where input, carry and output
    share one aval."""
    idx = _stage_ordinal(stage_id, axis_name)
    out_aval = jax.eval_shape(
        lambda x: stage_fn(jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype),
    )

    def stage_fn2(x_in, state):
        out = stage_fn(jnp.where(idx == 0, x_in, state))
        return out, out

    return pipeline_schedule_hetero(stage_fn2, x_mb, n_stages,
                                    out_aval, out_aval, axis_name,
                                    stage_id=idx, psum_fn=psum_fn)


def spmd_pipeline(stage_fn, mesh, n_stages, axis_name="pp",
                  params_spec=None, remat=False):
    """Build the jittable pipelined function over a mesh.

    stage_fn(stage_params, x) -> x, where stage_params is this stage's
    slice of leading-axis-stacked parameters.

    Returns pipelined(stacked_params, x_mb): stacked_params leading axis is
    sharded over `axis_name`; x_mb is [n_micro, ...] microbatches. Output
    is the last stage's [n_micro, ...], replicated over `axis_name`.
    """
    if params_spec is None:
        params_spec = P(axis_name)

    inner = stage_fn
    if remat:
        inner = jax.checkpoint(stage_fn)

    def body(ids, stacked_local, x_mb):
        def one_stage(x):
            return inner(stacked_local, x)

        return pipeline_schedule(one_stage, x_mb, n_stages, axis_name,
                                 stage_id=ids[0])

    sharded = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), params_spec, P()),
        out_specs=P(),
        axis_names={axis_name},
    )

    def pipelined(stacked_params, x_mb):
        return sharded(_stage_iota(n_stages), stacked_params, x_mb)

    return pipelined


def schedule_ticks(n_micro, n_stages):
    """Tick count of the plain schedule (each tick = FULL per-device stage)."""
    return n_micro + n_stages - 1


def interleaved_ticks(n_micro, pp, v):
    """Tick count of the circular/interleaved schedule (each tick = 1/v of a
    device's layers). Normalised bubble: (pp-1)/v small-ticks vs (pp-1) full
    ticks for the plain schedule — the VPP win
    (reference: pipeline_parallel.py:1308 PipelineParallelWithInterleave)."""
    return v * n_micro + pp - 1


def interleaved_pipeline_schedule(stage_fn, x_mb, pp, v, axis_name="pp",
                                  stage_id=None):
    """Circular (virtual-stage / VPP) schedule, run inside shard_map.

    Device s holds v chunks; chunk c acts as virtual stage c*pp + s. A
    microbatch makes v laps of the ring; lap l of microbatch m runs on
    device s at tick l*n_micro + m + s. Wrap-around activations (device
    pp-1 -> 0) wait n_micro - pp ticks in a rolling FIFO, so n_micro >= pp
    is required.

    stage_fn(chunk_idx, x) -> x (applies this device's chunk `chunk_idx`).
    x_mb: [n_micro, ...] stage-0 inputs (replicated over pp).
    """
    idx = _stage_ordinal(stage_id, axis_name)
    n_micro = x_mb.shape[0]
    if n_micro < pp:
        raise ValueError(
            f"interleaved schedule needs n_micro >= pp ({n_micro} < {pp})")
    total = interleaved_ticks(n_micro, pp, v)
    wait = n_micro - pp
    fifo_len = wait + 1
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    out_aval = jax.eval_shape(
        lambda x: stage_fn(jnp.zeros((), jnp.int32),
                           jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype),
    )

    def _z(shape):
        return jax.lax.pcast(
            jnp.zeros(shape, out_aval.dtype), axis_name, to="varying")

    state0 = _z(out_aval.shape)
    fifo0 = _z((fifo_len,) + tuple(out_aval.shape))
    out_buf0 = _z((n_micro,) + tuple(out_aval.shape))

    def tick(carry, t):
        fifo, state, out_buf = carry
        # incoming rotated activation -> FIFO slot t%len; device 0 pops the
        # one written `wait` ticks ago (lap wrap), others pop the newest
        w = jnp.mod(t, fifo_len)
        fifo = jax.lax.dynamic_update_index_in_dim(fifo, state, w, 0)
        r = jnp.where(idx == 0, jnp.mod(t - wait + fifo_len, fifo_len), w)
        queued = jax.lax.dynamic_index_in_dim(fifo, r, 0, keepdims=False)

        mb_new = jnp.clip(t, 0, n_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x_mb, mb_new, 0, keepdims=False)
        inp = jnp.where((idx == 0) & (t < n_micro), fresh, queued)

        rel = t - idx  # ticks since this device's first real work
        lap = jnp.clip((rel + v * n_micro) // n_micro - v, 0, v - 1)
        out = stage_fn(lap, inp)

        m = jnp.mod(rel + v * n_micro, n_micro)
        valid = ((idx == pp - 1) & (rel >= (v - 1) * n_micro)
                 & (rel < v * n_micro))
        cur = jax.lax.dynamic_index_in_dim(out_buf, m, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, out, cur), m, 0)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (fifo, state, out_buf), None

    (_, _, out_buf), _ = jax.lax.scan(
        tick, (fifo0, state0, out_buf0), jnp.arange(total))
    return jax.lax.psum(
        jnp.where(idx == pp - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name,
    )


def spmd_pipeline_interleaved(stage_fn, mesh, pp, v, axis_name="pp",
                              remat=False):
    """Jittable interleaved pipeline over leading-axis-stacked params.

    stage_fn(chunk_params, x) -> x where chunk_params is one chunk's slice
    [n_layers/(pp*v), ...] of each stacked param. The caller passes params
    stacked [L, ...] with L % (pp*v) == 0; virtual stage j gets layers
    [j*g, (j+1)*g), g = L/(pp*v), and device s holds chunks {c*pp+s}.
    """
    inner = jax.checkpoint(stage_fn) if remat else stage_fn

    def body(ids, stacked_local, x_mb):
        # local leaves arrive as [v, 1, g, ...] (axis 1 = this device's shard)
        local = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0],) + tuple(a.shape[2:])),
            stacked_local)

        def one_stage(lap, x):
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, lap, 0, keepdims=False),
                local)
            return inner(chunk, x)

        return interleaved_pipeline_schedule(one_stage, x_mb, pp, v,
                                             axis_name, stage_id=ids[0])

    def pipelined(stacked_params, x_mb):
        def split(a):
            L = a.shape[0]
            g = L // (pp * v)
            # [L, ...] -> [v, pp, g, ...]: layer j = (c*pp+s)*g + i lands at
            # [c, s, i] — device s's chunk c is virtual stage c*pp+s
            return a.reshape((v, pp, g) + tuple(a.shape[1:]))

        stacked = jax.tree_util.tree_map(split, stacked_params)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axis_name), P(None, axis_name), P()),
            out_specs=P(),
            axis_names={axis_name},
        )(_stage_iota(pp), stacked, x_mb)

    return pipelined


def microbatch(batch, n_micro, axis=0):
    """[B, ...] -> [n_micro, B/n_micro, ...]"""
    def _one(x):
        if x.ndim == 0:
            return x
        b = x.shape[axis]
        if b % n_micro != 0:
            raise ValueError(f"batch dim {b} not divisible by {n_micro} microbatches")
        return x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))

    return jax.tree_util.tree_map(_one, batch)


def unmicrobatch(mb):
    def _one(x):
        return x.reshape((-1,) + tuple(x.shape[2:]))

    return jax.tree_util.tree_map(_one, mb)


# ---------------------------------------------------------------------------
# Zero-bubble schedule (ZB-H1 analogue)
# ---------------------------------------------------------------------------
def zero_bubble_cost(n_micro, pp, v=1, cf=1.0, cb=2.0, cw_frac=1.0 / 3.0):
    """Normalised fwd+bwd cost of the zero-bubble schedule, in full-tick
    units (cf = one stage forward, cb = one stage full backward, of which
    cw_frac is the weight-grad share).

    ZB structure: the backward RING carries only dgrad (cost cb*(1-cw_frac)
    per tick); every weight grad runs AFTER the ring as one batched
    bubble-free contraction (cost n_micro * cb * cw_frac, no fill/drain).
    Composes with v-way interleaving: ring ticks shrink by 1/v.

    Reference: passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62 —
    same wgrad-off-the-critical-path idea, expressed as a compiled
    schedule instead of instruction reordering."""
    ring_ticks = (v * n_micro + pp - 1) / v
    dgrad = cb * (1.0 - cw_frac)
    return ring_ticks * (cf + dgrad) + n_micro * cb * cw_frac


def plain_cost(n_micro, pp, cf=1.0, cb=2.0):
    """Plain compiled ring: AD reverses the scan, every bwd tick carries
    dgrad AND wgrad."""
    return (n_micro + pp - 1) * (cf + cb)


def interleaved_cost(n_micro, pp, v, cf=1.0, cb=2.0):
    """AD-reversed interleaved ring in full-tick units."""
    return (v * n_micro + pp - 1) / v * (cf + cb)


def _zb_forward(inner, stacked_local, x_mb, n_stages, idx, axis_name):
    """Per-shard zero-bubble forward ring; also returns the per-tick
    stage inputs (stash). ``idx`` is this shard's stage ordinal."""
    n_micro = x_mb.shape[0]
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    out_aval = jax.eval_shape(
        lambda x: inner(stacked_local,
                        jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype))

    def _z(shape, dt):
        return jax.lax.pcast(jnp.zeros(shape, dt), axis_name,
                             to="varying")

    state0 = _z(out_aval.shape, out_aval.dtype)
    out_buf0 = _z((n_micro,) + tuple(out_aval.shape), out_aval.dtype)
    stash0 = _z((total,) + tuple(x_mb.shape[1:]), x_mb.dtype)

    def tick(carry, t):
        state, out_buf, stash = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0,
                                            keepdims=False)
        inp = jnp.where(idx == 0, x_in, state)
        stash = jax.lax.dynamic_update_index_in_dim(stash, inp, t, 0)
        out = inner(stacked_local, inp)
        o_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = (t >= n_stages - 1) & (idx == n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(out_buf, o_idx, 0,
                                           keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, out, cur), o_idx, 0)
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, out_buf, stash), None

    (state, out_buf, stash), _ = jax.lax.scan(
        tick, (state0, out_buf0, stash0), jnp.arange(total))
    out = jax.lax.psum(
        jnp.where(idx == n_stages - 1, out_buf,
                  jnp.zeros_like(out_buf)), axis_name)
    return out, stash


def _zb_backward(inner, stacked_local, stash, g_mb, n_stages, idx,
                 axis_name):
    """Per-shard reverse ring (dgrad only) + batched post-ring wgrad."""
    n_micro = g_mb.shape[0]
    total = n_micro + n_stages - 1
    # reverse routing: cotangent of stage s's input goes to stage s-1
    rperm = [(j, (j - 1) % n_stages) for j in range(n_stages)]

    def dx_of(act, g):
        _, pull = jax.vjp(lambda a: inner(stacked_local, a), act)
        (da,) = pull(g)
        return da

    g0 = jax.lax.pcast(jnp.zeros(g_mb.shape[1:], g_mb.dtype),
                       axis_name, to="varying")
    gbuf0 = jax.lax.pcast(
        jnp.zeros((total,) + tuple(g_mb.shape[1:]), g_mb.dtype),
        axis_name, to="varying")
    dxmb0 = jax.lax.pcast(jnp.zeros_like(g_mb), axis_name, to="varying")

    def tick(carry, u):
        g_state, g_used, dx_mb = carry
        t = total - 1 - u                      # mirrored fwd tick
        # microbatch handled by THIS device at fwd tick t
        m = t - idx
        m_valid = (m >= 0) & (m < n_micro)
        # last stage injects the loss cotangent for its microbatch
        g_inj = jax.lax.dynamic_index_in_dim(
            g_mb, jnp.clip(m, 0, n_micro - 1), 0, keepdims=False)
        g = jnp.where(idx == n_stages - 1, g_inj, g_state)
        g = jnp.where(m_valid, g, jnp.zeros_like(g))
        # record the (tick -> cotangent) pair for the post-ring wgrad
        g_used = jax.lax.dynamic_update_index_in_dim(g_used, g, t, 0)
        act = jax.lax.dynamic_index_in_dim(stash, t, 0, keepdims=False)
        da = dx_of(act, g)
        # stage 0's da is the cotangent of x_mb[m]
        put = (idx == 0) & m_valid
        mi = jnp.clip(m, 0, n_micro - 1)
        cur = jax.lax.dynamic_index_in_dim(dx_mb, mi, 0, keepdims=False)
        dx_mb = jax.lax.dynamic_update_index_in_dim(
            dx_mb, jnp.where(put, da, cur), mi, 0)
        g_state = jax.lax.ppermute(da, axis_name, rperm)
        return (g_state, g_used, dx_mb), None

    (g_state, g_used, dx_mb), _ = jax.lax.scan(
        tick, (g0, gbuf0, dxmb0), jnp.arange(total))

    # ---- wgrad: ONE batched vjp over every stashed pair (no ring,
    # no bubble; garbage ticks carry zero cotangents) ----
    def batched(params):
        return jax.vmap(lambda a: inner(params, a))(stash)

    _, pull = jax.vjp(batched, stacked_local)
    (dW,) = pull(g_used)
    dx_all = jax.lax.psum(dx_mb, axis_name)   # only stage 0 contributed
    return dW, dx_all


def _int_cotangent(x):
    """float0 cotangent for an integer operand of a custom_vjp (the
    stage-ordinal arg is int32 and has no gradient)."""
    import numpy as np

    return np.zeros(np.shape(x), jax.dtypes.float0)


def zero_bubble_schedule(stage_fn, stacked_local, x_mb, n_stages,
                         stage_id, axis_name="pp", remat=False):
    """Per-shard zero-bubble pipelined apply, for callers ALREADY inside
    a manual region over ``axis_name`` (the composed hybrid step,
    collectives/compose.py). Same split-backward structure as
    :func:`spmd_pipeline_zero_bubble`: the reverse ring carries dgrad
    only, weight grads batch after it, and the schedule is wrapped in a
    custom_vjp so AD never reverses the forward scan. ``stage_id`` is
    this shard's ordinal (traced; its cotangent is float0)."""
    inner = jax.checkpoint(stage_fn) if remat else stage_fn

    @jax.custom_vjp
    def pipelined(stacked_local, x_mb, sid):
        out, _ = _zb_forward(inner, stacked_local, x_mb, n_stages, sid,
                             axis_name)
        return out

    def pipelined_fwd(stacked_local, x_mb, sid):
        out, stash = _zb_forward(inner, stacked_local, x_mb, n_stages,
                                 sid, axis_name)
        return out, (stacked_local, stash, sid)

    def pipelined_bwd(res, g):
        stacked_local, stash, sid = res
        dW, dx = _zb_backward(inner, stacked_local, stash, g, n_stages,
                              sid, axis_name)
        return dW, dx, _int_cotangent(sid)

    pipelined.defvjp(pipelined_fwd, pipelined_bwd)
    return pipelined(stacked_local, x_mb, stage_id)


def spmd_pipeline_zero_bubble(stage_fn, mesh, n_stages, axis_name="pp",
                              params_spec=None, remat=False):
    """Zero-bubble pipelined function over leading-axis-stacked params.

    Same contract as `spmd_pipeline`: returns
    pipelined(stacked_params, x_mb) -> [n_micro, ...] last-stage outputs.
    The HAND-WRITTEN backward splits dgrad from wgrad: the reverse ring
    propagates activation cotangents only (short critical path), and all
    weight gradients are computed afterwards as ONE batched vjp over the
    stashed per-tick (input, cotangent) pairs — wgrad has no pipeline
    bubble at all, the ZB-H1 property in compiled-SPMD form.
    """
    if params_spec is None:
        params_spec = P(axis_name)
    inner = jax.checkpoint(stage_fn) if remat else stage_fn

    def _fwd_body(ids, stacked_local, x_mb):
        return _zb_forward(inner, stacked_local, x_mb, n_stages, ids[0],
                           axis_name)

    def _bwd_body(ids, stacked_local, stash, g_mb):
        return _zb_backward(inner, stacked_local, stash, g_mb, n_stages,
                            ids[0], axis_name)

    @jax.custom_vjp
    def pipelined(stacked_params, x_mb):
        out, _ = jax.shard_map(
            _fwd_body, mesh=mesh,
            in_specs=(P(axis_name), params_spec, P()),
            out_specs=(P(), P(axis_name)),
            axis_names={axis_name},
        )(_stage_iota(n_stages), stacked_params, x_mb)
        return out

    def pipelined_fwd(stacked_params, x_mb):
        out, stash = jax.shard_map(
            _fwd_body, mesh=mesh,
            in_specs=(P(axis_name), params_spec, P()),
            out_specs=(P(), P(axis_name)),
            axis_names={axis_name},
        )(_stage_iota(n_stages), stacked_params, x_mb)
        return out, (stacked_params, stash, x_mb)

    def pipelined_bwd(res, g):
        stacked_params, stash, x_mb = res
        dW, dx = jax.shard_map(
            _bwd_body, mesh=mesh,
            in_specs=(P(axis_name), params_spec, P(axis_name), P()),
            out_specs=(params_spec, P()),
            axis_names={axis_name},
        )(_stage_iota(n_stages), stacked_params, stash, g)
        return dW, dx

    pipelined.defvjp(pipelined_fwd, pipelined_bwd)
    return pipelined


def bubble_fraction_model(n_micro, pp, schedule="1f1b", v=1, cf=1.0,
                          cb=2.0, cw_frac=1.0 / 3.0):
    """Schedule idle fraction in tick units: (scheduled − useful work) /
    scheduled, per device. For the plain 1F1B ring this is exactly
    ``(pp−1)/(n_micro+pp−1)`` when cf/cb cancel; the zero-bubble
    schedule pays ring idleness only on (cf + dgrad) ticks — its wgrad
    runs bubble-free after the ring — so its fraction is structurally
    smaller for any positive ``cw_frac``. ``cf``/``cb``/``cw_frac`` may
    be MEASURED per-phase costs (:func:`bubble_report`)."""
    if schedule == "zb":
        total = zero_bubble_cost(n_micro, pp, v=v, cf=cf, cb=cb,
                                 cw_frac=cw_frac)
    elif v > 1:
        total = interleaved_cost(n_micro, pp, v, cf=cf, cb=cb)
    else:
        total = plain_cost(n_micro, pp, cf=cf, cb=cb)
    work = n_micro * (cf + cb)
    return max(0.0, 1.0 - work / total)


def bubble_report(pp, n_micro, schedule="1f1b", v=1, hidden=256,
                  layers_per_stage=4, rows=256, iters=5):
    """The bench ``"pipe"`` block's bubble accounting (docs/PIPELINE.md).

    Measures the per-phase stage costs — cf (forward), dgrad, wgrad —
    from small compiled programs on THIS host, then prices the engaged
    schedule's idle fraction with them via :func:`bubble_fraction_model`.
    The tick structure is the executed schedule's own (n_micro + pp − 1
    ring ticks); only the per-tick weights are measured. This is the
    honest bubble metric on every substrate: wall-clocking the whole
    ring on a host that multiplexes the virtual devices onto shared
    cores measures core contention, not idleness (docs/ZB_WALLCLOCK.md).

    Returns a JSON-able dict with the measured phase seconds, the
    engaged schedule's ``bubble_fraction``, the plain-1F1B budget
    ``(pp−1)/(n_micro+pp−1)``, and the zb-vs-1f1b comparison."""
    import time

    import numpy as np

    budget = (pp - 1) / (n_micro + pp - 1)
    out = {
        "pp": int(pp), "n_micro": int(n_micro), "schedule": schedule,
        "v": int(v), "bubble_budget_1f1b": budget,
    }
    try:
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.standard_normal(
            (layers_per_stage, hidden, hidden)).astype(np.float32) * 0.3)
        x = jnp.asarray(rng.standard_normal(
            (rows, hidden)).astype(np.float32))

        def stage(w, x):
            def step(c, w1):
                return jnp.tanh(c @ w1), None

            out, _ = jax.lax.scan(step, x, w)
            return out

        f_fwd = jax.jit(stage)
        f_dx = jax.jit(jax.grad(lambda x, w: jnp.sum(stage(w, x) ** 2)))
        f_dw = jax.jit(jax.grad(lambda w, x: jnp.sum(stage(w, x) ** 2)))

        def measure(fn, *args):
            jax.block_until_ready(fn(*args))
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(*args)
            jax.block_until_ready(r)
            return (time.perf_counter() - t0) / iters

        cf = measure(f_fwd, w, x)
        t_dx = measure(f_dx, x, w)
        t_dw = measure(f_dw, w, x)
        # grad programs re-run the forward: split out the backward parts
        cb_d = max(t_dx - cf, 1e-9)
        cb_w = max(t_dw - cf, 1e-9)
        cb = cb_d + cb_w
        out["measured"] = {
            "cf_seconds": cf, "dgrad_seconds": cb_d,
            "wgrad_seconds": cb_w, "iters": iters,
            "hidden": hidden, "layers_per_stage": layers_per_stage,
        }
        kw = dict(cf=cf, cb=cb, cw_frac=cb_w / cb)
    except Exception as e:  # pragma: no cover - measurement best-effort
        out["measured"] = None
        out["measure_error"] = f"{type(e).__name__}: {e}"
        kw = dict(cf=1.0, cb=2.0, cw_frac=1.0 / 3.0)
    out["bubble_fraction_1f1b"] = bubble_fraction_model(
        n_micro, pp, "1f1b", v=v, **kw)
    out["bubble_fraction_zb"] = bubble_fraction_model(
        n_micro, pp, "zb", v=v, **kw)
    out["bubble_fraction"] = out[
        "bubble_fraction_zb" if schedule == "zb"
        else "bubble_fraction_1f1b"]
    out["zb_beats_1f1b"] = (out["bubble_fraction_zb"]
                            < out["bubble_fraction_1f1b"])
    return out


def spmd_pipeline_zero_bubble_interleaved(stage_fn, mesh, pp, v,
                                          axis_name="pp", remat=False):
    """Zero-bubble over the circular (VPP) schedule: 1/v-sized ring ticks
    carrying forward (then dgrad-only in reverse), with every weight grad
    batched AFTER the ring. Combines both bubble shrinkers — cost
    ``zero_bubble_cost(n, pp, v)``, which beats plain interleaving at
    pp=4/n_micro=4 (15.5 vs 16.5 full-tick units at cb=2cf, cw=cb/3).

    Contract matches `spmd_pipeline_interleaved`:
    stage_fn(chunk_params, x) -> x over [L/(pp*v), ...] chunk slices of
    [L, ...]-stacked params.
    """
    if remat:
        # the dgrad ring and batched wgrad both re-run the chunk forward
        # through jax.vjp of the checkpointed fn — same policy semantics
        # as the AD schedules
        stage_fn = jax.checkpoint(stage_fn)

    def _split(a):
        L = a.shape[0]
        g = L // (pp * v)
        return a.reshape((v, pp, g) + tuple(a.shape[1:]))

    def _lap_of(t, idx, n_micro):
        rel = t - idx
        return jnp.clip((rel + v * n_micro) // n_micro - v, 0, v - 1), rel

    def _fwd_body(ids, stacked_local, x_mb):
        idx = ids[0]
        n_micro = x_mb.shape[0]
        if n_micro < pp:
            raise ValueError(
                f"interleaved zb needs n_micro >= pp ({n_micro} < {pp})")
        total = v * n_micro + pp - 1
        wait = n_micro - pp
        fifo_len = wait + 1
        perm = [(j, (j + 1) % pp) for j in range(pp)]

        local = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0],) + tuple(a.shape[2:])),
            stacked_local)

        def chunk_apply(lap, x):
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, lap, 0, keepdims=False), local)
            return stage_fn(chunk, x)

        out_aval = jax.eval_shape(
            lambda x: chunk_apply(jnp.zeros((), jnp.int32),
                                  jax.lax.pcast(x, axis_name, to="varying")),
            jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype))

        def _z(shape):
            return jax.lax.pcast(
                jnp.zeros(shape, out_aval.dtype), axis_name, to="varying")

        state0 = _z(out_aval.shape)
        fifo0 = _z((fifo_len,) + tuple(out_aval.shape))
        out_buf0 = _z((n_micro,) + tuple(out_aval.shape))
        stash0 = _z((total,) + tuple(x_mb.shape[1:]))

        def tick(carry, t):
            fifo, state, out_buf, stash = carry
            w = jnp.mod(t, fifo_len)
            fifo = jax.lax.dynamic_update_index_in_dim(fifo, state, w, 0)
            r = jnp.where(idx == 0, jnp.mod(t - wait + fifo_len, fifo_len), w)
            queued = jax.lax.dynamic_index_in_dim(fifo, r, 0, keepdims=False)
            mb_new = jnp.clip(t, 0, n_micro - 1)
            fresh = jax.lax.dynamic_index_in_dim(x_mb, mb_new, 0,
                                                 keepdims=False)
            inp = jnp.where((idx == 0) & (t < n_micro), fresh, queued)
            stash = jax.lax.dynamic_update_index_in_dim(stash, inp, t, 0)
            lap, rel = _lap_of(t, idx, n_micro)
            out = chunk_apply(lap, inp)
            m = jnp.mod(rel + v * n_micro, n_micro)
            valid = ((idx == pp - 1) & (rel >= (v - 1) * n_micro)
                     & (rel < v * n_micro))
            cur = jax.lax.dynamic_index_in_dim(out_buf, m, 0, keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, out, cur), m, 0)
            state = jax.lax.ppermute(out, axis_name, perm)
            return (fifo, state, out_buf, stash), None

        (_, _, out_buf, stash), _ = jax.lax.scan(
            tick, (fifo0, state0, out_buf0, stash0), jnp.arange(total))
        out = jax.lax.psum(
            jnp.where(idx == pp - 1, out_buf, jnp.zeros_like(out_buf)),
            axis_name)
        return out, stash

    def _bwd_body(ids, stacked_local, stash, g_mb):
        idx = ids[0]
        n_micro = g_mb.shape[0]
        total = v * n_micro + pp - 1
        wait = n_micro - pp
        fifo_len = wait + 1
        rperm = [(j, (j - 1) % pp) for j in range(pp)]

        local = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0],) + tuple(a.shape[2:])),
            stacked_local)

        def chunk_apply(params_local, lap, x):
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, lap, 0, keepdims=False), params_local)
            return stage_fn(chunk, x)

        def dx_of(lap, act, g):
            _, pull = jax.vjp(lambda a: chunk_apply(local, lap, a), act)
            (da,) = pull(g)
            return da

        g0 = jax.lax.pcast(jnp.zeros(g_mb.shape[1:], g_mb.dtype),
                           axis_name, to="varying")
        fifo0 = jax.lax.pcast(
            jnp.zeros((fifo_len,) + tuple(g_mb.shape[1:]), g_mb.dtype),
            axis_name, to="varying")
        gbuf0 = jax.lax.pcast(
            jnp.zeros((total,) + tuple(g_mb.shape[1:]), g_mb.dtype),
            axis_name, to="varying")
        dxmb0 = jax.lax.pcast(jnp.zeros_like(g_mb), axis_name, to="varying")

        def tick(carry, u):
            fifo, g_state, g_used, dx_mb = carry
            t = total - 1 - u
            # reverse wrap edge (0 -> pp-1) is delayed by `wait` ticks: the
            # mirror of the forward FIFO on the pp-1 -> 0 edge
            w = jnp.mod(u, fifo_len)
            fifo = jax.lax.dynamic_update_index_in_dim(fifo, g_state, w, 0)
            r = jnp.where(idx == pp - 1,
                          jnp.mod(u - wait + fifo_len, fifo_len), w)
            queued = jax.lax.dynamic_index_in_dim(fifo, r, 0, keepdims=False)

            lap, rel = _lap_of(t, idx, n_micro)
            real = (rel >= 0) & (rel < v * n_micro)
            m = jnp.mod(rel + v * n_micro, n_micro)
            # final-output cotangent injection mirrors the fwd out_buf write
            inject = ((idx == pp - 1) & (rel >= (v - 1) * n_micro)
                      & (rel < v * n_micro))
            g_inj = jax.lax.dynamic_index_in_dim(g_mb, m, 0, keepdims=False)
            g = jnp.where(inject, g_inj, queued)
            g = jnp.where(real, g, jnp.zeros_like(g))
            g_used = jax.lax.dynamic_update_index_in_dim(g_used, g, t, 0)

            act = jax.lax.dynamic_index_in_dim(stash, t, 0, keepdims=False)
            da = dx_of(lap, act, g)
            put = (idx == 0) & (t < n_micro)
            mi = jnp.clip(t, 0, n_micro - 1)
            cur = jax.lax.dynamic_index_in_dim(dx_mb, mi, 0, keepdims=False)
            dx_mb = jax.lax.dynamic_update_index_in_dim(
                dx_mb, jnp.where(put, da, cur), mi, 0)
            g_state = jax.lax.ppermute(da, axis_name, rperm)
            return (fifo, g_state, g_used, dx_mb), None

        (_, _, g_used, dx_mb), _ = jax.lax.scan(
            tick, (fifo0, g0, gbuf0, dxmb0), jnp.arange(total))

        # ---- batched wgrad per chunk: gather exactly the n_micro real
        # ticks of each chunk (tick of (chunk c, mb m) = c*n + m + idx) ----
        def dW_of():
            dWs = []
            for c in range(v):
                ticks = c * n_micro + jnp.arange(n_micro) + idx   # [n]
                acts = jnp.take(stash, ticks, axis=0)
                gs = jnp.take(g_used, ticks, axis=0)

                def batched(params_local):
                    chunk = jax.tree_util.tree_map(
                        lambda a: jax.lax.dynamic_index_in_dim(
                            a, c, 0, keepdims=False), params_local)
                    return jax.vmap(lambda a: stage_fn(chunk, a))(acts)

                _, pull = jax.vjp(batched, local)
                (dW_c,) = pull(gs)
                dWs.append(dW_c)
            # sum of per-chunk pullbacks: each wrote only its chunk's rows
            out = jax.tree_util.tree_map(lambda *xs: sum(xs), *dWs)
            return jax.tree_util.tree_map(
                lambda a: a.reshape((a.shape[0], 1) + tuple(a.shape[1:])),
                out)

        dW = dW_of()
        dx_all = jax.lax.psum(dx_mb, axis_name)
        return dW, dx_all

    def _shmap(body, out_specs):
        return functools.partial(
            jax.shard_map, body, mesh=mesh, axis_names={axis_name})

    @jax.custom_vjp
    def pipelined(stacked_params, x_mb):
        stacked = jax.tree_util.tree_map(_split, stacked_params)
        out, _ = jax.shard_map(
            _fwd_body, mesh=mesh,
            in_specs=(P(axis_name), P(None, axis_name), P()),
            out_specs=(P(), P(axis_name)),
            axis_names={axis_name},
        )(_stage_iota(pp), stacked, x_mb)
        return out

    def pipelined_fwd(stacked_params, x_mb):
        stacked = jax.tree_util.tree_map(_split, stacked_params)
        out, stash = jax.shard_map(
            _fwd_body, mesh=mesh,
            in_specs=(P(axis_name), P(None, axis_name), P()),
            out_specs=(P(), P(axis_name)),
            axis_names={axis_name},
        )(_stage_iota(pp), stacked, x_mb)
        return out, (stacked_params, stash, x_mb)

    def pipelined_bwd(res, g):
        stacked_params, stash, x_mb = res
        stacked = jax.tree_util.tree_map(_split, stacked_params)
        dW4, dx = jax.shard_map(
            _bwd_body, mesh=mesh,
            in_specs=(P(axis_name), P(None, axis_name), P(axis_name), P()),
            out_specs=(P(None, axis_name), P()),
            axis_names={axis_name},
        )(_stage_iota(pp), stacked, stash, g)
        dW = jax.tree_util.tree_map(
            lambda a, p: a.reshape(p.shape), dW4, stacked_params)
        return dW, dx

    pipelined.defvjp(pipelined_fwd, pipelined_bwd)
    return pipelined
