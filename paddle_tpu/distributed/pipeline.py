"""Compiled SPMD pipeline parallelism (the "pp" mesh axis).

Capability parity: the reference's pipeline runtimes — 1F1B
(`fleet/meta_parallel/pipeline_parallel.py:242`), interleave (:1308), and
the static zero-bubble schedule pass — are host-driven microbatch loops
over NCCL p2p. The TPU-native redesign compiles the ENTIRE schedule into
one SPMD program: every stage holds its layer shard (leading-axis sharding
over "pp"), activations rotate between neighbour chips with
``lax.ppermute`` (one ICI hop), and the fill/steady/drain phases are a
``lax.scan`` over ticks. XLA overlaps the ppermute transfer of tick t with
the stage compute of tick t+1 — the same overlap 1F1B gets from separate
comm streams, without the host scheduler, watchdogs, or p2p machinery.

Mapped only over "pp" (partial shard_map): dp/mp/sep shardings inside the
stage function remain visible to GSPMD and compose unchanged.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

P = PartitionSpec


def pipeline_schedule(stage_fn, x_mb, n_stages, axis_name="pp"):
    """Run inside shard_map over `axis_name`.

    stage_fn: activation -> activation (this device's layer shard applied).
    x_mb: [n_micro, ...] microbatched stage-0 input (replicated over pp).
    Returns [n_micro, ...] last-stage outputs, replicated over pp.

    Schedule: n_micro + n_stages - 1 ticks. Tick t: stage 0 ingests
    microbatch t, stage s processes the activation that entered at tick
    t - s, the last stage emits microbatch t - (n_stages - 1).
    """
    idx = jax.lax.axis_index(axis_name)
    n_micro = x_mb.shape[0]
    total = n_micro + n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    out_aval = jax.eval_shape(
        lambda x: stage_fn(jax.lax.pcast(x, axis_name, to="varying")),
        jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype),
    )
    state0 = jax.lax.pcast(
        jnp.zeros(out_aval.shape, out_aval.dtype), axis_name, to="varying"
    )
    out_buf0 = jax.lax.pcast(
        jnp.zeros((n_micro,) + tuple(out_aval.shape), out_aval.dtype),
        axis_name, to="varying",
    )

    def tick(carry, t):
        state, out_buf = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x_in = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(idx == 0, x_in, state)
        out = stage_fn(inp)
        o_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = (t >= n_stages - 1) & (idx == n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(out_buf, o_idx, 0, keepdims=False)
        out_buf = jax.lax.dynamic_update_index_in_dim(
            out_buf, jnp.where(valid, out, cur), o_idx, 0
        )
        state = jax.lax.ppermute(out, axis_name, perm)
        return (state, out_buf), None

    (state, out_buf), _ = jax.lax.scan(tick, (state0, out_buf0), jnp.arange(total))
    return jax.lax.psum(
        jnp.where(idx == n_stages - 1, out_buf, jnp.zeros_like(out_buf)),
        axis_name,
    )


def spmd_pipeline(stage_fn, mesh, n_stages, axis_name="pp",
                  params_spec=None, remat=False):
    """Build the jittable pipelined function over a mesh.

    stage_fn(stage_params, x) -> x, where stage_params is this stage's
    slice of leading-axis-stacked parameters.

    Returns pipelined(stacked_params, x_mb): stacked_params leading axis is
    sharded over `axis_name`; x_mb is [n_micro, ...] microbatches. Output
    is the last stage's [n_micro, ...], replicated over `axis_name`.
    """
    if params_spec is None:
        params_spec = P(axis_name)

    inner = stage_fn
    if remat:
        inner = jax.checkpoint(stage_fn)

    def body(stacked_local, x_mb):
        def one_stage(x):
            return inner(stacked_local, x)

        return pipeline_schedule(one_stage, x_mb, n_stages, axis_name)

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(params_spec, P()),
        out_specs=P(),
        axis_names={axis_name},
    )


def microbatch(batch, n_micro, axis=0):
    """[B, ...] -> [n_micro, B/n_micro, ...]"""
    def _one(x):
        if x.ndim == 0:
            return x
        b = x.shape[axis]
        if b % n_micro != 0:
            raise ValueError(f"batch dim {b} not divisible by {n_micro} microbatches")
        return x.reshape((n_micro, b // n_micro) + tuple(x.shape[1:]))

    return jax.tree_util.tree_map(_one, batch)


def unmicrobatch(mb):
    def _one(x):
        return x.reshape((-1,) + tuple(x.shape[2:]))

    return jax.tree_util.tree_map(_one, mb)
