"""Semi-automatic SPMD: DistTensor API over jax.sharding (GSPMD).

Capability parity with the reference's auto-parallel core
(`paddle/phi/core/distributed/auto_parallel/`: ProcessMesh
`process_mesh.h:34`, Placement/Shard/Replicate/Partial
`placement_types.h:37-133`, DistTensor `dist_tensor.h:39`, reshard engine
`reshard/*.cc`; python `python/paddle/distributed/auto_parallel/api.py:220
shard_tensor`, `:797 reshard`) — redesigned TPU-first:

- `ProcessMesh` wraps a `jax.sharding.Mesh` over the device grid.
- `Shard(d)/Replicate()/Partial()` placements translate to a
  `PartitionSpec`, one entry per *mesh* dim (paddle convention), mapped
  here onto the tensor-dim-indexed spec jax uses.
- `shard_tensor` is `jax.device_put` with a `NamedSharding` — the layout
  change rides ICI, scheduled by XLA, no hand-written reshard kernels.
- `reshard` between any two placements is again `device_put`: XLA emits
  the minimal collective (all-gather / reduce-scatter / all-to-all /
  ppermute), replacing the reference's 20+ pairwise `{s,r,p}_to_{s,r,p}`
  reshard functions with the compiler's general solution.
- The per-op SPMD rules (`paddle/phi/infermeta/spmd_rules/*`, 121 files)
  are delegated to GSPMD propagation inside jit; `shard_activation` is the
  explicit override hook (`lax.with_sharding_constraint`).
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .. import telemetry as _telemetry

# same metric families as distributed/communication (one registry: the
# names/labelnames must stay in sync — registry rejects a mismatch)
_TELEMETRY_REG = _telemetry.get_registry()
_COLL_CALLS = _telemetry.counter(
    "collective_calls_total", "eager collective API calls",
    labelnames=("op", "axis", "nranks"))
_COLL_BYTES = _telemetry.counter(
    "collective_bytes_total", "payload bytes entering eager collectives",
    labelnames=("op", "axis", "nranks"))


# ---------------------------------------------------------------------------
# Placements (reference: placement_types.h:37-133)
# ---------------------------------------------------------------------------
class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Partial(Placement):
    """Pending-reduction placement. Stored replicated (XLA resolves partial
    sums inside compiled programs; an eager Partial materialises the sum)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


# ---------------------------------------------------------------------------
# ProcessMesh (reference: process_mesh.h:34)
# ---------------------------------------------------------------------------
_global_mesh: Optional["ProcessMesh"] = None


class ProcessMesh:
    """An N-D grid of devices with named axes, backed by jax.sharding.Mesh.

    `mesh` is an int array of *device ids* (indices into the global device
    list — process ids in the reference's multi-proc-per-device world;
    identical here since jax is one process per host, many devices).
    """

    def __init__(self, mesh=None, dim_names=None, shape=None):
        if shape is not None:
            mesh = np.arange(int(np.prod(shape))).reshape(shape)
        self._mesh = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._mesh.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._mesh.shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def mesh(self):
        return self._mesh

    @property
    def process_ids(self):
        return self._mesh.flatten().tolist()

    def get_dim_size(self, name):
        return self._mesh.shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, name, index=None):
        """Sub-mesh slicing along a named axis (parity: process_mesh.py)."""
        axis = self._dim_names.index(name)
        moved = np.moveaxis(self._mesh, axis, 0)
        names = [name] + [n for n in self._dim_names if n != name]
        if index is None:
            return ProcessMesh(moved, names)
        return ProcessMesh(moved[index], names[1:])

    @property
    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = np.array(jax.devices(), dtype=object)[self._mesh]
            self._jax_mesh = Mesh(devices, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and np.array_equal(self._mesh, other._mesh)
            and self._dim_names == other._dim_names
        )

    def __hash__(self):
        return hash((self._mesh.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def auto_parallel_enabled():
    return _global_mesh is not None


# ---------------------------------------------------------------------------
# placements <-> PartitionSpec
# ---------------------------------------------------------------------------
def placements_to_spec(mesh: ProcessMesh, placements: Sequence[Placement]) -> PartitionSpec:
    """Paddle placements (indexed by MESH dim) -> jax PartitionSpec
    (indexed by TENSOR dim)."""
    by_tensor_dim = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            by_tensor_dim.setdefault(p.dim, []).append(mesh.dim_names[mesh_dim])
    if not by_tensor_dim:
        return PartitionSpec()
    ndim = max(by_tensor_dim) + 1
    entries = []
    for d in range(ndim):
        axes = by_tensor_dim.get(d, [])
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    return PartitionSpec(*entries)


def spec_to_placements(mesh: ProcessMesh, spec: PartitionSpec, ndim: int):
    placements = [Replicate() for _ in mesh.dim_names]
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tensor_dim)
    return placements


class TensorDistAttr:
    """Parity: phi TensorDistAttr (`dist_attr.h:36`) — mesh + placements."""

    def __init__(self, mesh: ProcessMesh, placements: Sequence[Placement]):
        self.process_mesh = mesh
        self.placements = list(placements)

    def __repr__(self):
        return f"TensorDistAttr(mesh={self.process_mesh}, placements={self.placements})"


# ---------------------------------------------------------------------------
# shard_tensor / reshard  (api.py:220, :797)
# ---------------------------------------------------------------------------
def _named_sharding(mesh: ProcessMesh, placements) -> NamedSharding:
    return NamedSharding(mesh.jax_mesh, placements_to_spec(mesh, placements))


def _check_placements(x, mesh: ProcessMesh, placements):
    """Clear errors for the two easy mistakes (parity with the reference's
    PADDLE_ENFORCE messages in dist_tensor.cc): shard dim out of range and
    non-divisible shard. GSPMD requires even shards; pad the tensor or pick
    a divisible dim."""
    shape = tuple(x._data.shape)
    for mesh_dim, p in enumerate(placements):
        if not isinstance(p, Shard):
            continue
        if p.dim >= len(shape):
            raise ValueError(
                f"Shard(dim={p.dim}) is out of range for tensor of rank "
                f"{len(shape)} (shape {list(shape)})"
            )
        size = mesh.shape[mesh_dim]
        if size > 1 and shape[p.dim] % size != 0:
            raise ValueError(
                f"cannot Shard(dim={p.dim}): tensor dim {shape[p.dim]} is not "
                f"divisible by mesh axis '{mesh.dim_names[mesh_dim]}' size "
                f"{size}. TPU/GSPMD shards must be even — pad the tensor or "
                f"choose a divisible dim."
            )


def shard_tensor(x, mesh: ProcessMesh, placements, stop_gradient=None):
    """Make a DistTensor: place `x` over `mesh` with `placements`.

    The result is still a paddle_tpu Tensor — its payload is a sharded
    jax.Array (GSPMD's DTensor equivalent), and `dist_attr` records the
    logical placement for parity with DistTensor (`dist_tensor.h:39`).
    """
    if not isinstance(x, Tensor):
        from ..ops.creation import to_tensor

        x = to_tensor(x)
    _check_placements(x, mesh, placements)
    arr = jax.device_put(x._data, _named_sharding(mesh, placements))
    out = Tensor(
        arr,
        stop_gradient=x.stop_gradient if stop_gradient is None else stop_gradient,
    )
    out._dist_attr = TensorDistAttr(mesh, placements)
    from ..core.tensor import Parameter

    if isinstance(x, Parameter):
        p = Parameter(arr, trainable=x.trainable, name=x.name)
        p._dist_attr = out._dist_attr
        return p
    return out


def reshard(x: Tensor, mesh: ProcessMesh, placements):
    """Transfer a DistTensor to new placements; XLA picks the collective
    (replaces the reference's pairwise reshard functions, reshard/*.cc)."""
    _check_placements(x, mesh, placements)
    has_partial = any(isinstance(p, Partial) for p in (
        x._dist_attr.placements if x._dist_attr else []))
    src_attr = x._dist_attr
    sharding = _named_sharding(mesh, placements)

    def _move(arr):
        if has_partial:
            # eager partial -> materialise the pending sum across partial axes
            arr = _resolve_partial(arr, src_attr)
        return jax.device_put(arr, sharding)

    # dispatch through the tape so resharding an activation keeps gradients
    from ..core.dispatch import apply_op

    out = apply_op(_move, x, _op_name="reshard")
    out.stop_gradient = x.stop_gradient
    out._dist_attr = TensorDistAttr(mesh, placements)
    return out


def _resolve_partial(arr, dist_attr):
    axes = [
        dist_attr.process_mesh.dim_names[i]
        for i, p in enumerate(dist_attr.placements)
        if isinstance(p, Partial)
    ]
    if not axes:
        return arr
    if _TELEMETRY_REG.enabled:
        # the reshard psum, labeled by the REAL mesh axes it reduces over
        nranks = int(np.prod([dist_attr.process_mesh.get_dim_size(a)
                              for a in axes]))
        labels = ("reshard_psum", "+".join(axes), str(nranks))
        _COLL_CALLS.inc(labels=labels)
        _COLL_BYTES.inc(int(getattr(arr, "nbytes", 0) or 0), labels=labels)
    mesh = dist_attr.process_mesh.jax_mesh
    from jax import shard_map

    spec = PartitionSpec()  # partial tensors are stored replicated per-shard

    def _sum(a):
        return jax.lax.psum(a, tuple(axes))

    return shard_map(
        _sum, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )(arr)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def dtensor_from_local(local_tensor, mesh, placements):
    """Assemble a global DistTensor from this process's local shard
    (parity: api.py dtensor_from_local; multi-controller path)."""
    arr = local_tensor._data if isinstance(local_tensor, Tensor) else jnp.asarray(local_tensor)
    sharding = _named_sharding(mesh, placements)
    global_shape = list(arr.shape)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            global_shape[p.dim] *= mesh.shape[mesh_dim]
    out_arr = jax.make_array_from_process_local_data(sharding, np.asarray(arr), tuple(global_shape))
    out = Tensor(out_arr)
    out._dist_attr = TensorDistAttr(mesh, placements)
    return out


def shard_activation(x, placements=None, mesh=None, spec=None):
    """Constrain an intermediate's sharding inside jit (GSPMD override hook —
    the explicit analogue of a per-op spmd_rule from ops.yaml).

    Works inside partial-manual shard_map regions too (e.g. the compiled
    pipeline keeps 'pp' manual while mp/dp stay auto): the constraint is
    then built over the tracing context's abstract mesh with any
    manual-axis entries stripped from the spec — constraining a manual
    axis there is meaningless (the program already IS per-shard in it)
    and a concrete-mesh constraint would reject the value's vma."""
    mesh = mesh or get_mesh()
    if mesh is None:
        return x
    if spec is None:
        spec = placements_to_spec(mesh, placements)
    is_tensor = isinstance(x, Tensor)
    arr = x._data if is_tensor else x
    use_mesh = mesh.jax_mesh
    abstract = jax.sharding.get_abstract_mesh()
    if abstract.empty:
        # legacy jax reports a permanently-empty abstract mesh, so the
        # manual-axis strip below can never engage — but a plain
        # constraint traced inside a manual shard_map region makes this
        # XLA's partitioner hard-abort (Check failed: IsManualSubgroup,
        # the pre-existing example-02 crash). The explicitly-tracked
        # region flag (collectives.manual_grad_region) is the authority
        # there: skip the hint entirely — per-shard code already holds
        # exactly its slice, and auto axes lose only a placement HINT.
        from . import collectives as _coll

        if _coll.in_manual_grad_region():
            return x
    manual = (set() if abstract.empty else {
        n for n, t in zip(abstract.axis_names, abstract.axis_types)
        if t == jax.sharding.AxisType.Manual})
    if manual:
        U = PartitionSpec.UNCONSTRAINED

        def _strip(e):
            if e is None or e is U:
                return e
            if isinstance(e, (tuple, list)):
                kept = tuple(a for a in e if a not in manual)
                return kept or None
            return None if e in manual else e

        spec = PartitionSpec(*[_strip(e) for e in spec])
        use_mesh = abstract
    arr = jax.lax.with_sharding_constraint(arr, NamedSharding(use_mesh, spec))
    if is_tensor:
        out = Tensor(arr, stop_gradient=x.stop_gradient)
        out._grad_node = x._grad_node
        out._out_index = x._out_index
        return out
    return arr


# ---------------------------------------------------------------------------
# shard_layer / shard_optimizer (api.py:908, :1735)
# ---------------------------------------------------------------------------
def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None, output_fn=None):
    """Shard every parameter of `layer` over `process_mesh`.

    `shard_fn(name, layer, mesh)` may re-place individual params; default
    replicates (GSPMD propagation then decides activation layouts)."""
    for name, sub in list(layer.named_sublayers(include_self=True)):
        if shard_fn is not None:
            shard_fn(name, sub, process_mesh)
        else:
            for pname, p in list(sub._parameters.items()):
                if p is None or p._dist_attr is not None:
                    continue
                sub._parameters[pname] = shard_tensor(
                    p, process_mesh, [Replicate() for _ in process_mesh.dim_names]
                )
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Mark optimizer states to follow their parameter's sharding. The
    functional update is elementwise, so GSPMD keeps slots aligned with
    params with no further work (ZeRO-style state sharding comes from the
    params' own placements)."""
    optimizer._sharded = True
    return optimizer
