"""Distributed checkpoint: sharded save + reshard-on-load.

Capability parity: `python/paddle/distributed/checkpoint/` —
`save_state_dict` (save_state_dict.py:135) writes per-rank local shards
plus a global `Metadata` of `LocalTensorMetadata/LocalTensorIndex`
(metadata.py:20-41); `load_state_dict` (load_state_dict.py:526) computes
the overlap between saved shards and the target distribution and reshards
on load, so mesh topology can change between save and resume.

TPU-native: the "local shards" are a `jax.Array`'s addressable shards —
their `.index` IS the global-offset box the reference tracks by hand.
Reshard-on-load places loaded values with the target array's sharding via
`device_put`; XLA moves bytes over ICI as needed.

Crash safety (docs/CHECKPOINT.md): every file lands via tmp-file +
``os.replace`` so a reader can never observe a torn write; payloads are
serialized (host-snapshotted) in the CALLER's thread before any async
hand-off; per-file CRC32C checksums ride in the metadata; transient
``OSError`` from the filesystem is retried with exponential backoff.
``CheckpointManager`` (manager.py) builds the per-step commit protocol,
retention and auto-resume on top of these primitives.
"""
from __future__ import annotations

import atexit
import dataclasses
import os
import pickle
import threading
import time
import zlib

import numpy as np

from ...core.tensor import Tensor

try:  # hardware CRC32C when available; zlib CRC32 otherwise
    import google_crc32c as _crc32c

    CHECKSUM_ALGO = "crc32c"
except ImportError:  # pragma: no cover - depends on container image
    _crc32c = None
    CHECKSUM_ALGO = "crc32"


def checksum_bytes(data: bytes, algo: str = None) -> int:
    """Checksum `data` with `algo` (default: this host's best). Returns
    None for an algo this host cannot compute — the validator then falls
    back to size-only rather than reporting false corruption on a
    machine without the hardware-CRC wheel."""
    algo = CHECKSUM_ALGO if algo is None else algo
    if algo == "crc32c":
        return int(_crc32c.value(data)) if _crc32c is not None else None
    if algo == "crc32":
        return zlib.crc32(data) & 0xFFFFFFFF
    return None


# Retry policy for transient filesystem errors (preempted-VM NFS blips,
# ENOSPC races with the retention GC on another host, ...).
DEFAULT_WRITE_RETRIES = 3
DEFAULT_RETRY_BACKOFF = 0.05

# Fault-injection seam: paddle_tpu.testing.chaos installs a callable
# ``hook(path, attempt)`` here that may raise; called once per write
# attempt BEFORE any bytes land, so an injected OSError exercises the
# retry path and a non-OSError kills the save with no partial file.
_WRITE_FAULT_HOOK = None


class MissingKeysError(KeyError):
    """A strict load found target keys with no (valid) saved payload."""

    def __init__(self, missing, path):
        super().__init__(sorted(missing))
        self.missing = sorted(missing)
        self.path = path

    def __str__(self):
        return (f"checkpoint at {self.path!r} is missing payload for "
                f"{len(self.missing)} key(s): {self.missing} "
                f"(pass strict=False to keep the live values)")


_METRICS = None


def _metrics():
    """Lazy telemetry families (docs/CHECKPOINT.md metric contract)."""
    global _METRICS
    if _METRICS is None:
        from ... import telemetry

        _METRICS = {
            "save_seconds": telemetry.histogram(
                "checkpoint_save_seconds",
                "wall time of one checkpoint save (serialize + write + "
                "commit)", labelnames=("mode",)),
            "bytes": telemetry.counter(
                "checkpoint_bytes_total",
                "bytes written to checkpoint storage"),
            "restores": telemetry.counter(
                "checkpoint_restores_total",
                "successful checkpoint restores"),
            "validation_failures": telemetry.counter(
                "checkpoint_validation_failures_total",
                "steps rejected at restore time (missing COMMIT, checksum "
                "mismatch, unreadable shard/metadata)"),
            "missing_keys": telemetry.counter(
                "checkpoint_missing_keys_total",
                "target keys a strict=False load left at their live values"),
        }
    return _METRICS


@dataclasses.dataclass
class LocalTensorMetadata:
    """The location of a local shard in the global tensor (metadata.py:20)."""

    global_offset: tuple
    local_shape: tuple
    dtype: str


@dataclasses.dataclass(frozen=True)
class LocalTensorIndex:
    """The identifier of a local shard (metadata.py:31)."""

    tensor_key: str
    global_offset: tuple


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict = dataclasses.field(default_factory=dict)
    storage_metadata: dict = dataclasses.field(default_factory=dict)
    flat_mapping: dict = dataclasses.field(default_factory=dict)
    # filename -> {"algo", "value", "nbytes"}; absent on pre-checksum
    # checkpoints (pickle restores the old __dict__ as-is), so readers go
    # through file_checksums_of().
    file_checksums: dict = dataclasses.field(default_factory=dict)


def file_checksums_of(meta) -> dict:
    return getattr(meta, "file_checksums", {}) or {}


def _to_array(v):
    if isinstance(v, Tensor):
        return v._data
    return v


def _rank():
    from .. import get_rank

    return get_rank()


def _shard_boxes(arr, is_coordinator=True):
    """[(global_offset, local_np_array)] for the shards this process owns,
    deduped across replicas. Fully-replicated values with no addressable
    replica-0 shard fall back to the full array on the COORDINATOR only —
    every rank writing the fallback box would land world-size copies of
    the same bytes on disk (the metadata dedup hides the waste but not
    the I/O)."""
    if not hasattr(arr, "addressable_shards"):
        if not is_coordinator:
            return []
        a = np.asarray(arr)
        return [((0,) * a.ndim, a)]
    boxes = []
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue
        idx = sh.index  # tuple of slices into the global shape
        offset = tuple(
            (s.start or 0) if isinstance(s, slice) else 0 for s in idx
        )
        boxes.append((offset, np.asarray(sh.data)))
    if not boxes and is_coordinator:  # fully replicated elsewhere
        a = np.asarray(arr)
        boxes = [((0,) * a.ndim, a)]
    return boxes


def _atomic_write_bytes(path, data, retries=None, backoff=None, fsync=True):
    """Write `data` to `path` via tmp + os.replace: readers see the old
    file or the new one, never a prefix. Transient OSError retries with
    exponential backoff. Returns bytes written."""
    retries = DEFAULT_WRITE_RETRIES if retries is None else int(retries)
    backoff = DEFAULT_RETRY_BACKOFF if backoff is None else float(backoff)
    tmp = f"{path}.tmp.{os.getpid()}"
    attempt = 0
    while True:
        try:
            hook = _WRITE_FAULT_HOOK
            if hook is not None:
                hook(path, attempt)
            with open(tmp, "wb") as f:
                f.write(data)
                if fsync:
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, path)
            return len(data)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            attempt += 1
            if attempt > retries:
                raise
            time.sleep(backoff * (2 ** (attempt - 1)))


# ---------------------------------------------------------------------------
# save: prepare (host snapshot, caller thread) / execute (any thread)
# ---------------------------------------------------------------------------
def _prepare_save(state_dict, path, coordinator_rank=0, unique_id=None):
    """Serialize this rank's shards + (coordinator) global metadata into a
    write plan. Runs in the CALLER's thread: after it returns, the live
    state may mutate freely — the plan holds host copies only."""
    rank = _rank()
    is_coord = rank == coordinator_rank
    if unique_id is None:
        unique_id = 0
    data_file = f"{rank}_{unique_id}.distcp"

    meta = Metadata()
    payload = {}
    for key, val in state_dict.items():
        arr = _to_array(val)
        if not hasattr(arr, "ndim"):
            arr = np.asarray(arr)
        dtype_name = str(np.dtype(arr.dtype).name) if not hasattr(
            arr.dtype, "name") else arr.dtype.name
        metas = []
        for offset, block in _shard_boxes(arr, is_coordinator=is_coord):
            metas.append(LocalTensorMetadata(offset, tuple(block.shape),
                                             dtype_name))
            meta.storage_metadata[LocalTensorIndex(key, offset)] = data_file
            payload[f"{key}|{','.join(map(str, offset))}"] = block
        meta.state_dict_metadata[key] = metas
        meta.flat_mapping[key] = tuple(getattr(arr, "shape", ()))

    payload_bytes = pickle.dumps(payload, protocol=4)
    meta.file_checksums[data_file] = {
        "algo": CHECKSUM_ALGO,
        "value": checksum_bytes(payload_bytes),
        "nbytes": len(payload_bytes),
    }

    # In a multi-controller run each process only sees its own addressable
    # shards, so the coordinator must merge every rank's metadata before
    # writing the global .metadata file (reference save_state_dict.py:252-275
    # all_gather_object + merge) — otherwise non-coordinator ranks' .distcp
    # files are written but never referenced and load silently zero-fills.
    from ..communication import _is_dist_multiprocess, all_gather_object

    if _is_dist_multiprocess():
        gathered = []
        all_gather_object(
            gathered,
            (meta.state_dict_metadata, meta.storage_metadata,
             meta.flat_mapping, meta.file_checksums),
        )
        if is_coord:
            merged = Metadata()
            for sd_meta, st_meta, flat, sums in gathered:
                for key, metas in sd_meta.items():
                    have = merged.state_dict_metadata.setdefault(key, [])
                    seen = {(tuple(m.global_offset), tuple(m.local_shape))
                            for m in have}
                    for m in metas:
                        sig = (tuple(m.global_offset), tuple(m.local_shape))
                        if sig not in seen:
                            have.append(m)
                            seen.add(sig)
                for idx, fn in st_meta.items():
                    # first writer wins: replicated (unsharded) values are
                    # saved by every rank; reference only one file per box
                    merged.storage_metadata.setdefault(idx, fn)
                merged.flat_mapping.update(flat)
                merged.file_checksums.update(sums)
            meta = merged

    meta_file = meta_bytes = None
    file_checksums = dict(meta.file_checksums)
    if is_coord:
        meta_file = f"{unique_id}.metadata"
        meta_bytes = pickle.dumps(meta, protocol=4)
        file_checksums[meta_file] = {
            "algo": CHECKSUM_ALGO,
            "value": checksum_bytes(meta_bytes),
            "nbytes": len(meta_bytes),
        }

    return {
        "path": path,
        "rank": rank,
        "is_coordinator": is_coord,
        "data_file": data_file,
        "data_bytes": payload_bytes,
        "meta_file": meta_file,
        "meta_bytes": meta_bytes,
        # every file THIS process knows the checksum of (on the
        # coordinator after the gather: all ranks' shard files + the
        # metadata file — exactly the COMMIT manifest)
        "file_checksums": file_checksums,
    }


def _execute_save(plan, write_retries=None, retry_backoff=None):
    """Write a `_prepare_save` plan to disk. Thread-safe; returns bytes."""
    nbytes = _atomic_write_bytes(
        os.path.join(plan["path"], plan["data_file"]), plan["data_bytes"],
        retries=write_retries, backoff=retry_backoff)
    if plan["meta_bytes"] is not None:
        nbytes += _atomic_write_bytes(
            os.path.join(plan["path"], plan["meta_file"]), plan["meta_bytes"],
            retries=write_retries, backoff=retry_backoff)
    _metrics()["bytes"].inc(nbytes)
    return nbytes


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False, write_retries=None,
                    retry_backoff=None):
    """Write this rank's shards + (on the coordinator) the global metadata."""
    os.makedirs(path, exist_ok=True)
    t0 = time.perf_counter()
    plan = _prepare_save(state_dict, path, coordinator_rank, unique_id)

    def _write():
        _execute_save(plan, write_retries, retry_backoff)
        _metrics()["save_seconds"].observe(
            time.perf_counter() - t0,
            labels=("async" if async_save else "sync",))

    if async_save:
        pend = _PendingSave(path)
        pend.thread = threading.Thread(
            target=pend.run, args=(_write,), daemon=True,
            name="ptpu-ckpt-save")
        pend.thread.start()
        _PENDING.append(pend)
    else:
        _write()
    return plan


class _PendingSave:
    """An in-flight async save: its thread + the exception it died with.
    Daemon threads so a hung filesystem cannot wedge interpreter exit —
    the atexit drain below is what guarantees completed-or-reported."""

    __slots__ = ("thread", "error", "path")

    def __init__(self, path):
        self.thread = None
        self.error = None
        self.path = path

    def run(self, fn):
        try:
            fn()
        except BaseException as e:  # held for wait_async_save to re-raise
            self.error = e


_PENDING = []


def wait_async_save():
    """Join every pending async save; re-raise the FIRST writer exception
    (a failed async save must not report success by silence)."""
    pending, _PENDING[:] = list(_PENDING), []
    first = None
    for p in pending:
        p.thread.join()
        if first is None and p.error is not None:
            first = p.error
    if first is not None:
        raise first


def _drain_at_exit():
    """Interpreter exit must not truncate an in-flight save: atexit runs
    before daemon threads are killed, so joining here rides out the last
    writes; a held exception is reported, not raised into shutdown."""
    try:
        wait_async_save()
    except BaseException:
        import traceback

        traceback.print_exc()


atexit.register(_drain_at_exit)


def _load_metadata(path):
    metas = []
    for fn in sorted(os.listdir(path)):
        if fn.endswith(".metadata"):
            with open(os.path.join(path, fn), "rb") as f:
                metas.append(pickle.load(f))
    if not metas:
        raise FileNotFoundError(f"no .metadata file under {path}")
    return metas


def saved_state_template(path):
    """{key: zero Tensor of the SAVED global shape/dtype} built from a
    checkpoint directory's metadata alone — the load target for reading
    a checkpoint whose layout no live model matches
    (CheckpointManager.read_state; docs/SCAN.md layout conversion)."""
    import jax.numpy as jnp
    import ml_dtypes  # noqa: F401  (registers bfloat16 et al with numpy)

    shapes = {}
    for meta in _load_metadata(path):
        for key, ms in meta.state_dict_metadata.items():
            for m in ms:
                end = tuple(int(o) + int(s) for o, s in
                            zip(m.global_offset, m.local_shape))
                cur = shapes.get(key)
                if cur is None:
                    shapes[key] = (end, m.dtype)
                else:
                    shapes[key] = (tuple(max(a, b)
                                         for a, b in zip(cur[0], end)),
                                   cur[1])
    return {key: Tensor(jnp.zeros(shape, np.dtype(dtype)))
            for key, (shape, dtype) in shapes.items()}


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False,
                    strict=True):
    """Fill `state_dict`'s tensors from a checkpoint, resharding on load.

    Every key present in both the checkpoint and `state_dict` is assembled
    from its saved shard boxes and placed with the TARGET tensor's current
    sharding — the save-time and load-time meshes are independent.

    strict=True (default): raise `MissingKeysError` listing every target
    key the checkpoint holds no payload for (after filling all the keys it
    does hold). strict=False keeps the live value for missing keys and
    counts them on ``checkpoint_missing_keys_total``.
    """
    import jax

    metas = _load_metadata(path)
    # merge all metadata files (multi-coordinator saves)
    files = {}
    shard_meta = {}
    for meta in metas:
        for idx, fn in meta.storage_metadata.items():
            files.setdefault(fn, []).append(idx)
        for key, m in meta.state_dict_metadata.items():
            shard_meta.setdefault(key, []).extend(m)

    # read the payloads lazily per file
    cache = {}

    def _payload(fn):
        if fn not in cache:
            with open(os.path.join(path, fn), "rb") as f:
                cache[fn] = pickle.load(f)
        return cache[fn]

    def _boxes_for(key):
        """[(offset, shape, file)] of every saved box of `key` (metadata only)."""
        out = []
        for fn, idxs in files.items():
            for idx in idxs:
                if idx.tensor_key != key:
                    continue
                for m in shard_meta.get(key, ()):
                    if tuple(m.global_offset) == tuple(idx.global_offset):
                        out.append((tuple(m.global_offset),
                                    tuple(m.local_shape), fn))
                        break
        return out

    def _fill(buf, buf_offset, key, boxes):
        """Copy the intersection of each saved box into `buf` (a local window
        of the global tensor starting at buf_offset). Returns hit count."""
        hits = 0
        for offset, shape, fn in boxes:
            if len(shape) != buf.ndim:
                continue
            lo = [max(o, bo) for o, bo in zip(offset, buf_offset)]
            hi = [min(o + s, bo + bs)
                  for o, s, bo, bs in zip(offset, shape, buf_offset, buf.shape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            block = _payload(fn).get(f"{key}|{','.join(map(str, offset))}")
            if block is None:
                continue
            src = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, offset))
            dst = tuple(slice(l - bo, h - bo)
                        for l, h, bo in zip(lo, hi, buf_offset))
            buf[dst] = block[src]
            hits += 1
        return hits

    missing = []
    for key, target in state_dict.items():
        if key not in shard_meta:
            missing.append(key)
            continue
        tarr = _to_array(target)
        global_shape = tuple(tarr.shape)
        boxes = _boxes_for(key)
        if not boxes:
            missing.append(key)
            continue

        # 0-d tensors: single box, no slicing
        if not global_shape:
            block = _payload(boxes[0][2]).get(
                f"{key}|{','.join(map(str, boxes[0][0]))}")
            if block is None:
                missing.append(key)
                continue
            if isinstance(target, Tensor):
                import jax.numpy as jnp

                target._data = jnp.asarray(np.asarray(block), dtype=tarr.dtype)
            else:
                np.copyto(state_dict[key], np.asarray(block))
            continue

        sharding = getattr(tarr, "sharding", None)
        shards = getattr(tarr, "addressable_shards", None)
        if (isinstance(target, Tensor) and shards is not None
                and sharding is not None and hasattr(sharding, "mesh")):
            # Per-shard assembly: materialize only the LOCAL windows each
            # addressable device needs (reference load_state_dict computes the
            # saved-box/needed-slice overlap the same way) — host memory stays
            # O(local shards), not O(global) × world_size.
            bufs = []
            total_hits = 0
            for sh in shards:
                off = tuple((s.start or 0) if isinstance(s, slice) else 0
                            for s in sh.index)
                shape = tuple(
                    ((s.stop if s.stop is not None else g)
                     - (s.start or 0)) if isinstance(s, slice) else 1
                    for s, g in zip(sh.index, global_shape)
                )
                buf = np.zeros(shape, tarr.dtype)
                total_hits += _fill(buf, off, key, boxes)
                bufs.append(jax.device_put(buf, sh.device))
            if total_hits == 0:
                missing.append(key)  # payload missing: keep the live value
                continue
            target._data = jax.make_array_from_single_device_arrays(
                global_shape, sharding, bufs)
            continue

        # unsharded / numpy target: assemble the full value
        out = np.zeros(global_shape,
                       tarr.dtype if hasattr(tarr, "dtype") else np.float32)
        if _fill(out, (0,) * len(global_shape), key, boxes) == 0:
            missing.append(key)
            continue
        if isinstance(target, Tensor):
            import jax.numpy as jnp

            new = jnp.asarray(out, dtype=tarr.dtype)
            if sharding is not None and hasattr(sharding, "mesh"):
                new = jax.device_put(new, sharding)
            target._data = new
        else:
            np.copyto(state_dict[key], out)

    if missing:
        if strict:
            raise MissingKeysError(missing, path)
        _metrics()["missing_keys"].inc(len(missing))
    return state_dict


# ---------------------------------------------------------------------------
# Whole-training-state checkpoint (model + optimizer), reshard-on-load.
# Optimizer slots are keyed by MODEL state_dict name — stable across process
# restarts and topology changes — never by Parameter.name (a process-global
# counter). Reference capability: paddle.distributed.checkpoint save/load of
# master weights + accumulators (dist_checkpoint save_state_dict.py metadata
# contract extended to opt state).
# ---------------------------------------------------------------------------
def optimizer_state_dict(model, optimizer):
    """Flatten optimizer slots as {"opt.<param_name>.<slot>": Tensor}."""
    import jax.numpy as jnp

    out = {}
    for n, p in model.state_dict().items():
        for k, v in (optimizer._slots.get(id(p)) or {}).items():
            out[f"opt.{n}.{k}"] = Tensor(jnp.asarray(
                v._data if isinstance(v, Tensor) else v))
    return out


def training_state_dict(model, optimizer=None, train_step=None):
    """Model + optimizer state as one flat state_dict (the unit
    CheckpointManager saves per step). Pass the live TrainStep/
    ShardedTrainStep so its compiled-state slots are synced first."""
    if train_step is not None:
        train_step.sync_optimizer_state()
    state = dict(model.state_dict())
    if optimizer is not None:
        state.update(optimizer_state_dict(model, optimizer))
    return state


def _training_state_target(model, optimizer=None):
    """(target state_dict, finalize) for restoring model + optimizer:
    `finalize()` writes restored slot tensors back into the optimizer."""
    target = dict(model.state_dict())
    placeholders = {}
    if optimizer is not None:
        for n, p in model.state_dict().items():
            slots = optimizer._slots.get(id(p))
            if slots is None:
                slots = optimizer._init_slots(p._data)
                optimizer._slots[id(p)] = slots
            for k, v in slots.items():
                t = Tensor(_to_array(v))
                target[f"opt.{n}.{k}"] = t
                placeholders[(n, k, id(p))] = t

    def finalize():
        if optimizer is not None:
            for (n, k, pid), t in placeholders.items():
                optimizer._slots[pid][k] = t._data

    return target, finalize


def save_checkpoint(path, model, optimizer=None, train_step=None,
                    async_save=False):
    """Sharded save of model (+ optimizer) training state.

    Pass the live TrainStep/ShardedTrainStep as `train_step` so its
    compiled-state slots are synced into the optimizer first."""
    state = training_state_dict(model, optimizer, train_step)
    save_state_dict(state, path, async_save=async_save)


def load_checkpoint(path, model, optimizer=None, strict=True):
    """Reshard-on-load restore of model (+ optimizer) training state.

    Works across topology changes: every target tensor's CURRENT sharding
    decides which saved shards each rank reads. A subsequent TrainStep
    seeds its compiled state from the restored slots (jit._init_opt_state)."""
    target, finalize = _training_state_target(model, optimizer)
    load_state_dict(target, path, strict=strict)
    finalize()


from .manager import (  # noqa: E402,F401
    CheckpointManager,
    CheckpointValidationError,
    NoCheckpointError,
    PreemptionGuard,
)
