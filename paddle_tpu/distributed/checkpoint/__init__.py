"""Distributed checkpoint: sharded save + reshard-on-load.

Capability parity: `python/paddle/distributed/checkpoint/` —
`save_state_dict` (save_state_dict.py:135) writes per-rank local shards
plus a global `Metadata` of `LocalTensorMetadata/LocalTensorIndex`
(metadata.py:20-41); `load_state_dict` (load_state_dict.py:526) computes
the overlap between saved shards and the target distribution and reshards
on load, so mesh topology can change between save and resume.

TPU-native: the "local shards" are a `jax.Array`'s addressable shards —
their `.index` IS the global-offset box the reference tracks by hand.
Reshard-on-load places loaded values with the target array's sharding via
`device_put`; XLA moves bytes over ICI as needed.
"""
from __future__ import annotations

import dataclasses
import os
import pickle
import threading

import numpy as np

from ...core.tensor import Tensor


@dataclasses.dataclass
class LocalTensorMetadata:
    """The location of a local shard in the global tensor (metadata.py:20)."""

    global_offset: tuple
    local_shape: tuple
    dtype: str


@dataclasses.dataclass(frozen=True)
class LocalTensorIndex:
    """The identifier of a local shard (metadata.py:31)."""

    tensor_key: str
    global_offset: tuple


@dataclasses.dataclass
class Metadata:
    state_dict_metadata: dict = dataclasses.field(default_factory=dict)
    storage_metadata: dict = dataclasses.field(default_factory=dict)
    flat_mapping: dict = dataclasses.field(default_factory=dict)


def _to_array(v):
    if isinstance(v, Tensor):
        return v._data
    return v


def _rank():
    from .. import get_rank

    return get_rank()


def _shard_boxes(arr):
    """[(global_offset, local_np_array)] for the shards this process owns,
    deduped across replicas."""
    import jax

    if not hasattr(arr, "addressable_shards"):
        a = np.asarray(arr)
        return [((0,) * a.ndim, a)]
    boxes = []
    for sh in arr.addressable_shards:
        if sh.replica_id != 0:
            continue
        idx = sh.index  # tuple of slices into the global shape
        offset = tuple(
            (s.start or 0) if isinstance(s, slice) else 0 for s in idx
        )
        boxes.append((offset, np.asarray(sh.data)))
    if not boxes:  # fully replicated elsewhere; rank 0 fallback
        a = np.asarray(arr)
        boxes = [((0,) * a.ndim, a)]
    return boxes


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """Write this rank's shards + (on the coordinator) the global metadata."""
    os.makedirs(path, exist_ok=True)
    rank = _rank()
    if unique_id is None:
        unique_id = 0
    data_file = f"{rank}_{unique_id}.distcp"

    meta = Metadata()
    payload = {}
    for key, val in state_dict.items():
        arr = _to_array(val)
        if not hasattr(arr, "ndim"):
            arr = np.asarray(arr)
        dtype_name = str(np.dtype(arr.dtype).name) if not hasattr(
            arr.dtype, "name") else arr.dtype.name
        metas = []
        for offset, block in _shard_boxes(arr):
            metas.append(LocalTensorMetadata(offset, tuple(block.shape),
                                             dtype_name))
            meta.storage_metadata[LocalTensorIndex(key, offset)] = data_file
            payload[f"{key}|{','.join(map(str, offset))}"] = block
        meta.state_dict_metadata[key] = metas
        meta.flat_mapping[key] = tuple(getattr(arr, "shape", ()))

    # In a multi-controller run each process only sees its own addressable
    # shards, so the coordinator must merge every rank's metadata before
    # writing the global .metadata file (reference save_state_dict.py:252-275
    # all_gather_object + merge) — otherwise non-coordinator ranks' .distcp
    # files are written but never referenced and load silently zero-fills.
    from ..communication import _is_dist_multiprocess, all_gather_object

    if _is_dist_multiprocess():
        gathered = []
        all_gather_object(
            gathered,
            (meta.state_dict_metadata, meta.storage_metadata, meta.flat_mapping),
        )
        if rank == coordinator_rank:
            merged = Metadata()
            for sd_meta, st_meta, flat in gathered:
                for key, metas in sd_meta.items():
                    have = merged.state_dict_metadata.setdefault(key, [])
                    seen = {(tuple(m.global_offset), tuple(m.local_shape))
                            for m in have}
                    for m in metas:
                        sig = (tuple(m.global_offset), tuple(m.local_shape))
                        if sig not in seen:
                            have.append(m)
                            seen.add(sig)
                for idx, fn in st_meta.items():
                    # first writer wins: replicated (unsharded) values are
                    # saved by every rank; reference only one file per box
                    merged.storage_metadata.setdefault(idx, fn)
                merged.flat_mapping.update(flat)
            meta = merged

    def _write():
        with open(os.path.join(path, data_file), "wb") as f:
            pickle.dump(payload, f, protocol=4)
        if rank == coordinator_rank:
            with open(os.path.join(path, f"{unique_id}.metadata"), "wb") as f:
                pickle.dump(meta, f, protocol=4)

    if async_save:
        t = threading.Thread(target=_write, daemon=True)
        t.start()
        _PENDING.append(t)
    else:
        _write()


_PENDING = []


def wait_async_save():
    for t in _PENDING:
        t.join()
    _PENDING.clear()


def _load_metadata(path):
    metas = []
    for fn in sorted(os.listdir(path)):
        if fn.endswith(".metadata"):
            with open(os.path.join(path, fn), "rb") as f:
                metas.append(pickle.load(f))
    if not metas:
        raise FileNotFoundError(f"no .metadata file under {path}")
    return metas


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, unique_id=None, offload=False):
    """Fill `state_dict`'s tensors from a checkpoint, resharding on load.

    Every key present in both the checkpoint and `state_dict` is assembled
    from its saved shard boxes and placed with the TARGET tensor's current
    sharding — the save-time and load-time meshes are independent.
    """
    import jax

    metas = _load_metadata(path)
    # merge all metadata files (multi-coordinator saves)
    files = {}
    shard_meta = {}
    for meta in metas:
        for idx, fn in meta.storage_metadata.items():
            files.setdefault(fn, []).append(idx)
        for key, m in meta.state_dict_metadata.items():
            shard_meta.setdefault(key, []).extend(m)

    # read the payloads lazily per file
    cache = {}

    def _payload(fn):
        if fn not in cache:
            with open(os.path.join(path, fn), "rb") as f:
                cache[fn] = pickle.load(f)
        return cache[fn]

    def _boxes_for(key):
        """[(offset, shape, file)] of every saved box of `key` (metadata only)."""
        out = []
        for fn, idxs in files.items():
            for idx in idxs:
                if idx.tensor_key != key:
                    continue
                for m in shard_meta.get(key, ()):
                    if tuple(m.global_offset) == tuple(idx.global_offset):
                        out.append((tuple(m.global_offset),
                                    tuple(m.local_shape), fn))
                        break
        return out

    def _fill(buf, buf_offset, key, boxes):
        """Copy the intersection of each saved box into `buf` (a local window
        of the global tensor starting at buf_offset). Returns hit count."""
        hits = 0
        for offset, shape, fn in boxes:
            if len(shape) != buf.ndim:
                continue
            lo = [max(o, bo) for o, bo in zip(offset, buf_offset)]
            hi = [min(o + s, bo + bs)
                  for o, s, bo, bs in zip(offset, shape, buf_offset, buf.shape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            block = _payload(fn).get(f"{key}|{','.join(map(str, offset))}")
            if block is None:
                continue
            src = tuple(slice(l - o, h - o) for l, h, o in zip(lo, hi, offset))
            dst = tuple(slice(l - bo, h - bo)
                        for l, h, bo in zip(lo, hi, buf_offset))
            buf[dst] = block[src]
            hits += 1
        return hits

    for key, target in state_dict.items():
        if key not in shard_meta:
            continue
        tarr = _to_array(target)
        global_shape = tuple(tarr.shape)
        boxes = _boxes_for(key)
        if not boxes:
            continue

        # 0-d tensors: single box, no slicing
        if not global_shape:
            block = _payload(boxes[0][2]).get(
                f"{key}|{','.join(map(str, boxes[0][0]))}")
            if block is None:
                continue
            if isinstance(target, Tensor):
                import jax.numpy as jnp

                target._data = jnp.asarray(np.asarray(block), dtype=tarr.dtype)
            else:
                np.copyto(state_dict[key], np.asarray(block))
            continue

        sharding = getattr(tarr, "sharding", None)
        shards = getattr(tarr, "addressable_shards", None)
        if (isinstance(target, Tensor) and shards is not None
                and sharding is not None and hasattr(sharding, "mesh")):
            # Per-shard assembly: materialize only the LOCAL windows each
            # addressable device needs (reference load_state_dict computes the
            # saved-box/needed-slice overlap the same way) — host memory stays
            # O(local shards), not O(global) × world_size.
            bufs = []
            total_hits = 0
            for sh in shards:
                off = tuple((s.start or 0) if isinstance(s, slice) else 0
                            for s in sh.index)
                shape = tuple(
                    ((s.stop if s.stop is not None else g)
                     - (s.start or 0)) if isinstance(s, slice) else 1
                    for s, g in zip(sh.index, global_shape)
                )
                buf = np.zeros(shape, tarr.dtype)
                total_hits += _fill(buf, off, key, boxes)
                bufs.append(jax.device_put(buf, sh.device))
            if total_hits == 0:
                continue  # payload missing/mismatched: keep the live value
            target._data = jax.make_array_from_single_device_arrays(
                global_shape, sharding, bufs)
            continue

        # unsharded / numpy target: assemble the full value
        out = np.zeros(global_shape,
                       tarr.dtype if hasattr(tarr, "dtype") else np.float32)
        if _fill(out, (0,) * len(global_shape), key, boxes) == 0:
            continue
        if isinstance(target, Tensor):
            import jax.numpy as jnp

            new = jnp.asarray(out, dtype=tarr.dtype)
            if sharding is not None and hasattr(sharding, "mesh"):
                new = jax.device_put(new, sharding)
            target._data = new
        else:
            np.copyto(state_dict[key], out)
    return state_dict


# ---------------------------------------------------------------------------
# Whole-training-state checkpoint (model + optimizer), reshard-on-load.
# Optimizer slots are keyed by MODEL state_dict name — stable across process
# restarts and topology changes — never by Parameter.name (a process-global
# counter). Reference capability: paddle.distributed.checkpoint save/load of
# master weights + accumulators (dist_checkpoint save_state_dict.py metadata
# contract extended to opt state).
# ---------------------------------------------------------------------------
def optimizer_state_dict(model, optimizer):
    """Flatten optimizer slots as {"opt.<param_name>.<slot>": Tensor}."""
    import jax.numpy as jnp

    out = {}
    for n, p in model.state_dict().items():
        for k, v in (optimizer._slots.get(id(p)) or {}).items():
            out[f"opt.{n}.{k}"] = Tensor(jnp.asarray(
                v._data if isinstance(v, Tensor) else v))
    return out


def save_checkpoint(path, model, optimizer=None, train_step=None,
                    async_save=False):
    """Sharded save of model (+ optimizer) training state.

    Pass the live TrainStep/ShardedTrainStep as `train_step` so its
    compiled-state slots are synced into the optimizer first."""
    if train_step is not None:
        train_step.sync_optimizer_state()
    state = dict(model.state_dict())
    if optimizer is not None:
        state.update(optimizer_state_dict(model, optimizer))
    save_state_dict(state, path, async_save=async_save)


def load_checkpoint(path, model, optimizer=None):
    """Reshard-on-load restore of model (+ optimizer) training state.

    Works across topology changes: every target tensor's CURRENT sharding
    decides which saved shards each rank reads. A subsequent TrainStep
    seeds its compiled state from the restored slots (jit._init_opt_state)."""
    target = dict(model.state_dict())
    placeholders = {}
    if optimizer is not None:
        for n, p in model.state_dict().items():
            slots = optimizer._slots.get(id(p))
            if slots is None:
                slots = optimizer._init_slots(p._data)
                optimizer._slots[id(p)] = slots
            for k, v in slots.items():
                t = Tensor(_to_array(v))
                target[f"opt.{n}.{k}"] = t
                placeholders[(n, k, id(p))] = t
    load_state_dict(target, path)
    if optimizer is not None:
        for (n, k, pid), t in placeholders.items():
            optimizer._slots[pid][k] = t._data
