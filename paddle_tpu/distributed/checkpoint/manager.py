"""Crash-safe per-step checkpointing: commit protocol, retention, resume.

`CheckpointManager` owns a checkpoint ROOT of per-step directories::

    root/
      step_00000040/
        0_0.distcp      # per-rank shard payload (pickle, atomic-replaced)
        0.metadata      # global Metadata incl. per-file checksums
        COMMIT          # JSON manifest, written LAST — the commit point
      step_00000050/    # no COMMIT yet: invisible to every reader

The invariant readers rely on: a step directory is either COMMITTED —
its COMMIT manifest lists every file with size + CRC32C, all of which
validate — or it does not exist as far as `latest_step()`/`restore()`
are concerned. Every file is written tmp + ``os.replace`` and COMMIT is
written strictly after the shards it names, so no kill point (SIGKILL
mid-save, interpreter exit during an async save, torn filesystem) can
produce a loadable partial step. `restore()` walks committed steps
newest-first and falls back past any step that fails validation,
counting ``checkpoint_validation_failures_total``.

Async saves go through a bounded background writer: the state is
serialized to host IN THE CALLER'S THREAD (`_prepare_save`), so training
may mutate parameters immediately; only the disk I/O and the commit run
in the background. Writer exceptions re-raise on `wait()` and the writer
is drained at interpreter exit. `PreemptionGuard` turns SIGTERM/SIGINT
(and an optional wall-clock deadline) into a final synchronous save at
the next step boundary — the restart-based recovery contract of
fleet/elastic (SURVEY §5).

Fault-injection hooks for all of this live in `paddle_tpu.testing.chaos`;
`tools/ckpt_inspect.py` validates a root offline. Layout + contract:
docs/CHECKPOINT.md.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import pickle
import shutil
import signal
import threading
import time
import weakref

from ...telemetry import trace as _trace


class CheckpointValidationError(RuntimeError):
    """A step directory failed commit/checksum validation."""

    def __init__(self, step, problems):
        super().__init__(
            f"checkpoint step {step} failed validation: {'; '.join(problems)}")
        self.step = step
        self.problems = list(problems)


class NoCheckpointError(FileNotFoundError):
    """No committed-and-valid step exists under the root."""


class _AsyncWriter:
    """One background thread draining a bounded queue of save jobs.

    - `submit` blocks once `max_pending` jobs are outstanding — a slow
      filesystem applies backpressure to the train loop instead of
      accumulating unbounded host snapshots.
    - The first job exception is held and re-raised by `wait()` (and by
      the next `submit`), never swallowed.
    """

    def __init__(self, max_pending=2):
        self._max = max(1, int(max_pending))
        self._cv = threading.Condition()
        self._queue = collections.deque()
        self._outstanding = 0
        self._error = None
        self._thread = None
        self._closed = False

    def submit(self, fn):
        with self._cv:
            self._raise_held()
            while self._outstanding >= self._max:
                self._cv.wait()
                self._raise_held()
            if self._closed:
                raise RuntimeError("checkpoint writer is closed")
            self._outstanding += 1
            self._queue.append(fn)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ptpu-ckpt-writer")
                self._thread.start()
            self._cv.notify_all()

    def _raise_held(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _run(self):
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
                fn = self._queue.popleft()
            try:
                fn()
            except BaseException as e:
                with self._cv:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cv:
                    self._outstanding -= 1
                    self._cv.notify_all()

    def wait(self):
        """Block until every submitted job finished; re-raise the first
        writer exception."""
        with self._cv:
            while self._outstanding:
                self._cv.wait()
            self._raise_held()

    def pending(self) -> int:
        with self._cv:
            return self._outstanding

    def close(self):
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=30)


_LIVE_MANAGERS = weakref.WeakSet()
_ATEXIT_ARMED = False


def _drain_managers_at_exit():
    for mgr in list(_LIVE_MANAGERS):
        try:
            mgr.wait()
        except BaseException:
            import traceback

            traceback.print_exc()


def _arm_atexit():
    global _ATEXIT_ARMED
    if not _ATEXIT_ARMED:
        atexit.register(_drain_managers_at_exit)
        _ATEXIT_ARMED = True


class CheckpointManager:
    """Commit-marked, checksummed, retained per-step checkpoints.

    Args:
        root: checkpoint directory (created if missing).
        keep: retain only the newest N committed steps (None = keep all).
        keep_period: additionally always retain steps where
            ``step % keep_period == 0`` (archival anchors past `keep`).
        max_pending: bound on in-flight async saves before `save`
            blocks (backpressure).
        write_retries / retry_backoff: transient-OSError retry policy
            passed down to every file write.
        coordinator_rank: rank that writes metadata + COMMIT + runs GC.
    """

    COMMIT_FILE = "COMMIT"
    BAD_FILE = "BAD"
    STEP_PREFIX = "step_"
    STEP_DIGITS = 8

    def __init__(self, root, keep=None, keep_period=None, max_pending=2,
                 write_retries=None, retry_backoff=None, coordinator_rank=0):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = None if keep is None else int(keep)
        self.keep_period = None if keep_period is None else int(keep_period)
        self.coordinator_rank = int(coordinator_rank)
        self._write_retries = write_retries
        self._retry_backoff = retry_backoff
        self._writer = _AsyncWriter(max_pending)
        self._inflight = set()  # steps being written (never GC'd)
        self._inflight_lock = threading.Lock()
        self._bad_steps = set()  # guard-marked; also persisted as BAD files
        _LIVE_MANAGERS.add(self)
        _arm_atexit()

    # -- layout --------------------------------------------------------------
    def step_dir(self, step) -> str:
        return os.path.join(
            self.root, f"{self.STEP_PREFIX}{int(step):0{self.STEP_DIGITS}d}")

    def _parse_step(self, name):
        if not name.startswith(self.STEP_PREFIX):
            return None
        try:
            return int(name[len(self.STEP_PREFIX):])
        except ValueError:
            return None

    def _commit_path(self, step) -> str:
        return os.path.join(self.step_dir(step), self.COMMIT_FILE)

    def is_committed(self, step) -> bool:
        return os.path.exists(self._commit_path(step))

    def all_steps(self, committed_only=True):
        """Sorted step numbers present under the root."""
        steps = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            s = self._parse_step(name)
            if s is None:
                continue
            if committed_only and not self.is_committed(s):
                continue
            steps.append(s)
        return sorted(steps)

    def latest_step(self):
        """Newest COMMITTED step, or None. Uncommitted (in-flight or
        crashed) step directories are invisible here by construction."""
        steps = self.all_steps(committed_only=True)
        return steps[-1] if steps else None

    # -- guard-marked bad steps ----------------------------------------------
    def _bad_path(self, step) -> str:
        return os.path.join(self.step_dir(step), self.BAD_FILE)

    def is_bad(self, step) -> bool:
        return int(step) in self._bad_steps or os.path.exists(
            self._bad_path(step))

    def mark_bad(self, step, reason=""):
        """Exclude a committed step from `restore_last_good` (and from
        `restore`'s fallback walk): the resilience guard calls this when
        a rewind target did not cure a recurring anomaly — the
        checkpoint itself is suspect. Persisted as a BAD marker file in
        the step dir, so a restarted process skips it too."""
        step = int(step)
        self._bad_steps.add(step)
        if os.path.isdir(self.step_dir(step)):
            try:
                _atomic_write_marker = json.dumps(
                    {"step": step, "ts": time.time(),
                     "reason": str(reason)}).encode()
                from . import _atomic_write_bytes

                _atomic_write_bytes(self._bad_path(step),
                                    _atomic_write_marker, fsync=False)
            except OSError:
                pass  # the in-memory mark still applies this process
        return step

    def _clear_bad(self, step):
        """Forget a BAD verdict once a NEW commit lands at `step`: the
        marker described the state that commit just replaced. Called
        after the commit fence only — clearing earlier could resurrect
        the suspect old checkpoint if the overwrite died half-way."""
        step = int(step)
        self._bad_steps.discard(step)
        try:
            os.remove(self._bad_path(step))
        except OSError:
            pass

    def good_steps(self, before_step=None):
        """Committed steps not marked bad, oldest first; `before_step`
        keeps only steps strictly below it."""
        return [s for s in self.all_steps(committed_only=True)
                if not self.is_bad(s)
                and (before_step is None or s < int(before_step))]

    def last_good_step(self, before_step=None):
        good = self.good_steps(before_step)
        return good[-1] if good else None

    # -- save ----------------------------------------------------------------
    def save(self, step, state_dict, async_save=False):
        """Write `state_dict` as step `step`: shards, metadata, then the
        COMMIT manifest. async_save=True returns once the state is
        snapshotted to host; the writes + commit run on the bounded
        background writer (`wait()` surfaces any failure)."""
        from . import _metrics, _prepare_save

        from ..communication import _is_dist_multiprocess

        step = int(step)
        path = self.step_dir(step)
        os.makedirs(path, exist_ok=True)
        if async_save and _is_dist_multiprocess():
            # Multi-controller: the commit fence is a collective, and
            # collectives must stay on the thread that runs the training
            # collectives — a background fence would pair up with the
            # main thread's psums on other ranks and deadlock. Degrade
            # to a synchronous save (still atomic + committed).
            async_save = False
        t0 = time.perf_counter()
        # ckpt:snapshot = host serialization in the caller's thread (the
        # part that stalls training); ckpt:write_commit = disk I/O +
        # commit, on the writer thread for async saves — the span tracer
        # is thread-aware, so both land on the right timeline row
        with _trace.span("ckpt:snapshot",
                         attrs={"step": step}, cat="ckpt"):
            plan = _prepare_save(state_dict, path,
                                 coordinator_rank=self.coordinator_rank)
        with self._inflight_lock:
            self._inflight.add(step)

        def _finish():
            try:
                with _trace.span("ckpt:write_commit",
                                 attrs={"step": step,
                                        "async": bool(async_save)},
                                 cat="ckpt"):
                    self._write_and_commit(step, plan)
                _metrics()["save_seconds"].observe(
                    time.perf_counter() - t0,
                    labels=("async" if async_save else "sync",))
            finally:
                with self._inflight_lock:
                    self._inflight.discard(step)

        if async_save:
            self._writer.submit(_finish)
        else:
            _finish()
        return path

    def _write_and_commit(self, step, plan):
        from . import _execute_save
        from ..communication import _is_dist_multiprocess, all_gather_object

        _execute_save(plan, self._write_retries, self._retry_backoff)
        if _is_dist_multiprocess():
            # commit barrier: COMMIT must not exist until EVERY rank's
            # shard file is durably in place
            fence = []
            all_gather_object(fence, ("ckpt_commit", step))
        if plan["is_coordinator"]:
            self._write_commit(step, plan)
        # a guard rollback replay can legitimately re-save a step number
        # that was marked BAD: the fresh commit IS the cure, so the stale
        # verdict must not keep hiding it from restore/rollback/retention
        self._clear_bad(step)
        if plan["is_coordinator"]:
            self.gc()

    def _write_commit(self, step, plan):
        from . import CHECKSUM_ALGO, _atomic_write_bytes, _metrics

        manifest = {
            "step": step,
            "ts": time.time(),
            "algo": CHECKSUM_ALGO,
            "files": {fn: dict(info)
                      for fn, info in sorted(plan["file_checksums"].items())},
        }
        data = json.dumps(manifest, indent=1, sort_keys=True).encode()
        nbytes = _atomic_write_bytes(
            self._commit_path(step), data,
            retries=self._write_retries, backoff=self._retry_backoff)
        _metrics()["bytes"].inc(nbytes)

    def save_training_state(self, step, model, optimizer=None,
                            train_step=None, async_save=False):
        """`save()` of model + optimizer state (slots synced from the live
        TrainStep first) — the whole-train-loop convenience."""
        from . import training_state_dict

        state = training_state_dict(model, optimizer, train_step)
        return self.save(step, state, async_save=async_save)

    def wait(self):
        """Drain pending async saves; re-raise the first writer failure."""
        self._writer.wait()

    def close(self):
        try:
            self.wait()
        finally:
            self._writer.close()
            _LIVE_MANAGERS.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:  # don't mask the in-flight exception with writer errors
            try:
                self.close()
            except BaseException:
                pass
        return False

    # -- validation ----------------------------------------------------------
    def validate_step(self, step) -> list:
        """Problems with step `step` ([] = valid): COMMIT present and
        parseable, every manifest file present with matching size and
        checksum, metadata unpicklable, and every shard file the metadata
        references listed in the manifest."""
        from . import checksum_bytes

        path = self.step_dir(step)
        commit_path = self._commit_path(step)
        if not os.path.isdir(path):
            return ["step directory missing"]
        if not os.path.exists(commit_path):
            return ["uncommitted (no COMMIT marker)"]
        problems = []
        try:
            with open(commit_path, "rb") as f:
                manifest = json.loads(f.read().decode())
            files = manifest["files"]
        except (OSError, ValueError, KeyError) as e:
            return [f"unreadable COMMIT manifest: {e!r}"]
        for fn, info in sorted(files.items()):
            fp = os.path.join(path, fn)
            try:
                with open(fp, "rb") as f:
                    data = f.read()
            except OSError as e:
                problems.append(f"{fn}: unreadable ({e.strerror})")
                continue
            if len(data) != int(info["nbytes"]):
                problems.append(
                    f"{fn}: size {len(data)} != recorded {info['nbytes']}")
                continue
            got = checksum_bytes(data, algo=info.get("algo"))
            if got is not None and got != int(info["value"]):
                problems.append(f"{fn}: {info.get('algo', 'crc')} mismatch")
                continue
            if fn.endswith(".metadata"):
                try:
                    meta = pickle.loads(data)
                except Exception as e:
                    problems.append(f"{fn}: unpicklable ({e!r})")
                    continue
                for idx, ref in meta.storage_metadata.items():
                    if ref not in files:
                        problems.append(
                            f"{fn}: references {ref} for "
                            f"{idx.tensor_key!r} but the COMMIT manifest "
                            f"does not list it")
                        break
        return problems

    # -- restore -------------------------------------------------------------
    def restore(self, state_dict, step=None, strict=True, fallback=True):
        """Fill `state_dict` from the newest committed-and-valid step
        (or from `step` exactly). A step failing validation — or blowing
        up mid-load on corrupt bytes — counts one
        ``checkpoint_validation_failures_total`` and falls back to the
        previous committed step (unless `fallback=False` or `step` was
        explicit, which raise). Returns the step restored."""
        from . import MissingKeysError, _metrics, load_state_dict

        if step is not None:
            candidates = [int(step)]
        else:
            # fallback walk skips guard-marked-bad steps: auto-resuming
            # into a state the guard rewound away from would replay the
            # poisoning (restore_last_good below is the guard's entry)
            candidates = list(reversed(self.good_steps()))
        if not candidates:
            raise NoCheckpointError(
                f"no committed checkpoint step under {self.root!r}")
        return self._restore_candidates(
            state_dict, candidates, strict=strict,
            fallback=fallback and step is None)

    def _restore_candidates(self, state_dict, candidates, strict=True,
                            fallback=True, target_factory=None):
        """Walk `candidates` (newest first) validating + loading; with
        `fallback` a failing step counts a validation failure and the
        walk continues, else it raises. ``target_factory(step)``
        overrides the load target per candidate (read_state's
        metadata-derived template; ``state_dict`` is ignored then)."""
        from . import MissingKeysError, _metrics, load_state_dict

        last_err = None
        for s in candidates:
            with _trace.span("ckpt:validate", attrs={"step": s},
                             cat="ckpt"):
                problems = self.validate_step(s)
            if problems:
                _metrics()["validation_failures"].inc()
                last_err = CheckpointValidationError(s, problems)
                if not fallback:
                    raise last_err
                continue
            try:
                target = (state_dict if target_factory is None
                          else target_factory(s))
                with _trace.span("ckpt:load", attrs={"step": s},
                                 cat="ckpt"):
                    load_state_dict(target, self.step_dir(s),
                                    strict=strict)
            except MissingKeysError:
                raise  # wrong state shape, not corruption: older steps
                       # would silently resurrect stale values
            except Exception as e:
                # unpicklable/truncated payload that still matched its
                # checksum cannot happen; anything else here is a read
                # error — treat as validation failure and fall back
                _metrics()["validation_failures"].inc()
                last_err = CheckpointValidationError(s, [repr(e)])
                if not fallback:
                    raise last_err
                continue
            _metrics()["restores"].inc()
            return s
        raise NoCheckpointError(
            f"no committed step under {self.root!r} passed validation "
            f"(last error: {last_err})")

    def saved_keys(self, step=None):
        """Key set of the newest committed good step (or exactly
        `step`), from metadata alone — no payload reads, no validation.
        Lets callers decide HOW to restore (e.g. the cross-layout
        detection in models/gpt.py) before paying for a load."""
        from . import _load_metadata

        s = int(step) if step is not None else self.last_good_step()
        if s is None:
            raise NoCheckpointError(
                f"no committed checkpoint step under {self.root!r}")
        keys = set()
        for meta in _load_metadata(self.step_dir(s)):
            keys.update(meta.state_dict_metadata)
        return keys

    def read_state(self, step=None):
        """(state, step): the newest committed-and-valid step's raw
        arrays keyed by their SAVED names — no target model required
        (the metadata alone provides every key's global shape + dtype).
        The cross-layout restore entry: models/gpt.py
        ``restore_decoder_any_layout`` converts the result between the
        stacked and per-layer decoder layouts (docs/SCAN.md). With
        ``step=None`` corrupt steps fall back like ``restore``."""
        from . import saved_state_template

        if step is not None:
            candidates = [int(step)]
        else:
            candidates = list(reversed(self.good_steps()))
        if not candidates:
            raise NoCheckpointError(
                f"no committed checkpoint step under {self.root!r}")
        loaded = {}

        def factory(s):
            loaded.clear()
            loaded.update(saved_state_template(self.step_dir(s)))
            return loaded

        s = self._restore_candidates(None, candidates,
                                     fallback=step is None,
                                     target_factory=factory)
        return dict(loaded), s

    def restore_last_good(self, model, optimizer=None, before_step=None,
                          strict=True):
        """Restore model (+ optimizer) from the newest committed step the
        guard has NOT marked bad — optionally strictly before
        `before_step` (the anomalous step a rewind must land under).
        Corrupt steps fall back like `restore`; returns the step
        restored. The resilience guard's escalation entry point."""
        from . import _training_state_target

        candidates = list(reversed(self.good_steps(before_step)))
        if not candidates:
            raise NoCheckpointError(
                f"no good committed step under {self.root!r}"
                + ("" if before_step is None
                   else f" before step {int(before_step)}"))
        target, finalize = _training_state_target(model, optimizer)
        s = self._restore_candidates(target, candidates, strict=strict)
        finalize()
        return s

    def restore_training_state(self, model, optimizer=None, step=None,
                               strict=True):
        """`restore()` into model + optimizer (slot tensors written back);
        returns the step restored. The next TrainStep seeds its compiled
        state from the restored slots (jit._init_opt_state)."""
        from . import _training_state_target

        target, finalize = _training_state_target(model, optimizer)
        s = self.restore(target, step=step, strict=strict)
        finalize()
        return s

    # -- retention -----------------------------------------------------------
    def gc(self):
        """Apply retention: drop committed steps beyond `keep` (modulo
        `keep_period` anchors) and uncommitted debris older than the
        newest committed step. The `keep` window counts only GOOD steps —
        a guard-marked BAD step must not crowd a rollback target out of
        retention (with `keep` set, BAD steps beyond the window are
        collected like any excess step; `keep=None` keeps everything).
        In-flight saves are never collected."""
        committed = self.all_steps(committed_only=True)
        if not committed:
            return []
        newest = committed[-1]
        if self.keep is None:
            keep = set(committed)
        else:
            good = [s for s in committed if not self.is_bad(s)]
            keep = set(good[-self.keep:])
        if self.keep_period:
            keep.update(s for s in committed
                        if s % self.keep_period == 0 and not self.is_bad(s))
        with self._inflight_lock:
            keep.update(self._inflight)
        removed = []
        for name in sorted(os.listdir(self.root)):
            s = self._parse_step(name)
            if s is None or s in keep:
                continue
            if not self.is_committed(s) and s >= newest:
                continue  # in-flight from another process: leave it
            shutil.rmtree(os.path.join(self.root, name), ignore_errors=True)
            removed.append(s)
        return removed


class PreemptionGuard:
    """Preemption-aware clean shutdown for a training loop.

    SIGTERM/SIGINT (the preemption notices of every scheduler this
    framework targets) set a flag; the loop polls at step boundaries and
    performs ONE final synchronous save before exiting cleanly — signal
    handlers themselves never touch the filesystem. An optional
    ``max_seconds`` budget (e.g. the advance notice a TPU VM gets)
    triggers the same path when the remaining budget no longer covers
    another step plus ``margin`` seconds for the save itself.

    Usage::

        with PreemptionGuard(manager, max_seconds=None) as guard:
            for step in range(start + 1, total + 1):
                loss = train_one(step)
                if guard.checkpoint_and_stop(step, state_fn()):
                    break   # committed final state; exit cleanly

    A second signal while the final save runs restores the previous
    handler, so a stuck save can still be interrupted.
    """

    def __init__(self, manager=None, signals=(signal.SIGTERM, signal.SIGINT),
                 max_seconds=None, margin=5.0):
        self.manager = manager
        self.signals = tuple(signals)
        self.margin = float(margin)
        self._deadline = (time.monotonic() + float(max_seconds)
                          if max_seconds else None)
        self._preempted = False
        self._signum = None
        self._old = {}
        self._last_check = None
        self._max_step_seconds = 0.0
        self._flight_dumped = False

    # -- signal plumbing -----------------------------------------------------
    def _handler(self, signum, frame):
        self._preempted = True
        self._signum = signum
        # next delivery falls through to the previous behaviour
        old = self._old.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, old)
        except (ValueError, OSError):  # pragma: no cover - non-main thread
            pass

    def install(self):
        for s in self.signals:
            self._old[s] = signal.signal(s, self._handler)
        return self

    def uninstall(self):
        for s, old in self._old.items():
            try:
                if signal.getsignal(s) == self._handler:
                    signal.signal(s, old)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._old.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- loop interface ------------------------------------------------------
    @property
    def preempted(self) -> bool:
        return self._preempted

    @property
    def signum(self):
        return self._signum

    def should_stop(self) -> bool:
        """True once a signal arrived or the deadline no longer covers
        another step + save margin. Call once per step."""
        now = time.monotonic()
        if self._last_check is not None:
            self._max_step_seconds = max(self._max_step_seconds,
                                         now - self._last_check)
        self._last_check = now
        if self._preempted:
            self._note_flight("signal")
            return True
        if self._deadline is not None:
            if now + self._max_step_seconds + self.margin >= self._deadline:
                self._note_flight("deadline")
                return True
        return False

    def _note_flight(self, why):
        """One forensics bundle per preemption, from the POLL site —
        the signal handler itself must never touch the filesystem."""
        if self._flight_dumped:
            return
        self._flight_dumped = True
        from ...telemetry import flight as _flight
        _flight.maybe_dump("preemption", {
            "why": why, "signum": self._signum,
            "max_step_seconds": round(self._max_step_seconds, 3),
            "margin": self.margin})

    def checkpoint_and_stop(self, step, state_dict) -> bool:
        """If stopping: drain pending async saves, write `state_dict` as a
        SYNCHRONOUS committed step, and return True (caller breaks and
        exits cleanly). Otherwise False."""
        if not self.should_stop():
            return False
        if self.manager is not None:
            self.manager.wait()
            self.manager.save(step, state_dict, async_save=False)
        return True
