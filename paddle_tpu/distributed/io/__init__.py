"""distributed.io (parity: python/paddle/distributed/io.py): save/load for
distributed programs — delegates to the framework io + dist checkpoint."""
from ...framework_io import load, save  # noqa: F401
from ..checkpoint import load_state_dict, save_state_dict  # noqa: F401


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    raise NotImplementedError("static PS persistables: use paddle.save / "
                              "distributed.save_state_dict")


def load_persistables(*a, **k):
    raise NotImplementedError("static PS persistables: use paddle.load / "
                              "distributed.load_state_dict")


def is_persistable(var):
    return True
