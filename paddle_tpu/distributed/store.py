"""TCPStore: rendezvous key-value store (parity: phi TCPStore
`tcp_store.h:121`, python `paddle.distributed.TCPStore`).

Server: native C++ poll loop (core/native/store.cc) when the toolchain is
available, else an in-process Python thread speaking the same protocol.
Client: Python sockets (control-plane only — tensor traffic never touches
the store).
"""
from __future__ import annotations

import socket
import struct
import threading
import time

_CMD_SET, _CMD_GET, _CMD_ADD, _CMD_WAIT, _CMD_DEL, _CMD_PING = 0, 1, 2, 3, 4, 6
_MISS = 0xFFFFFFFFFFFFFFFF


class _PyServer:
    """Python fallback server, protocol-compatible with store.cc."""

    def __init__(self, port):
        self._kv = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(128)
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop:
                head = _recv_exact(conn, 5)
                if head is None:
                    return
                cmd, klen = struct.unpack("<BI", head)
                key = _recv_exact(conn, klen).decode()
                (vlen,) = struct.unpack("<Q", _recv_exact(conn, 8))
                val = _recv_exact(conn, vlen) if vlen else b""
                self._handle(conn, cmd, key, val)
        except (OSError, AttributeError):
            pass
        finally:
            conn.close()

    def _handle(self, conn, cmd, key, val):
        def reply(v):
            conn.sendall(struct.pack("<Q", len(v)) + v)

        with self._cond:
            if cmd == _CMD_SET:
                self._kv[key] = val
                self._cond.notify_all()
                reply(b"")
            elif cmd == _CMD_GET:
                if key in self._kv:
                    reply(self._kv[key])
                else:
                    conn.sendall(struct.pack("<Q", _MISS))
            elif cmd == _CMD_ADD:
                delta = struct.unpack("<q", val)[0] if len(val) == 8 else 0
                cur = struct.unpack("<q", self._kv.get(key, b"\0" * 8))[0]
                cur += delta
                self._kv[key] = struct.pack("<q", cur)
                self._cond.notify_all()
                reply(self._kv[key])
            elif cmd == _CMD_WAIT:
                while key not in self._kv and not self._stop:
                    self._cond.wait(timeout=0.2)
                reply(self._kv.get(key, b""))
            elif cmd == _CMD_DEL:
                self._kv.pop(key, None)
                reply(b"")
            elif cmd == _CMD_PING:
                reply(b"pong")
            else:
                conn.sendall(struct.pack("<Q", _MISS))

    def stop(self):
        self._stop = True
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None if not buf else buf
        buf += chunk
    return buf


class TCPStore:
    """paddle.distributed.TCPStore parity.

    is_master=True starts the server (native C++ if available); every rank
    connects a client. add/get/set/wait match the reference semantics.
    """

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=30.0, clock=None):
        self._server = None
        self._native_handle = None
        self.host = host
        self.timeout = timeout
        # connect/wait deadlines are measured on a monotonic clock: a
        # wall-clock step (NTP) must not hang or instantly expire a
        # rendezvous wait. `clock` is injectable for tests.
        self._clock = clock if clock is not None else time.monotonic
        if is_master:
            from ..core import native

            if native.available():
                import ctypes

                out_port = ctypes.c_int(0)
                h = native.LIB.pt_store_server_start(
                    int(port), ctypes.byref(out_port))
                if h:
                    self._native_handle = h
                    port = out_port.value
                else:  # e.g. port in use
                    self._server = _PyServer(port)
                    port = self._server.port
            else:
                self._server = _PyServer(port)
                port = self._server.port
        self.port = port
        self._sock = None
        # one request/response in flight per client: heartbeat threads
        # (fleet.elastic) share the store with the main thread
        self._lock = threading.Lock()
        self._connect()

    @property
    def is_native(self):
        return self._native_handle is not None

    def _connect(self):
        deadline = self._clock() + self.timeout
        last = None
        while self._clock() < deadline:
            try:
                s = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock = s
                return
            except OSError as e:
                last = e
                time.sleep(0.05)
        raise TimeoutError(f"TCPStore connect to {self.host}:{self.port}: {last}")

    def _req(self, cmd, key, val=b""):
        k = key.encode()
        msg = struct.pack("<BI", cmd, len(k)) + k + struct.pack("<Q", len(val)) + val
        with self._lock:
            self._sock.sendall(msg)
            (vlen,) = struct.unpack("<Q", _recv_exact(self._sock, 8))
            if vlen == _MISS:
                return None
            return _recv_exact(self._sock, vlen) if vlen else b""

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._req(_CMD_SET, key, value)

    def get(self, key):
        return self._req(_CMD_GET, key)

    def add(self, key, amount=1):
        out = self._req(_CMD_ADD, key, struct.pack("<q", int(amount)))
        return struct.unpack("<q", out)[0]

    def wait(self, key, timeout=None):
        """Block until `key` exists. Client-side poll (get + sleep) rather
        than the server's blocking WAIT: the per-client lock is released
        between probes, so threads sharing this store (e.g. the elastic
        heartbeat) are not starved for the duration.

        The poll interval backs off exponentially (20ms -> 500ms) so many
        ranks parked on one rendezvous key don't multiply load on the
        single-threaded server. ``timeout=float('inf')`` (or any
        non-finite value) waits forever — the rendezvous-style contract
        the reference's blocking WAIT provides (tcp_store.h:121)."""
        import math

        t = timeout if timeout is not None else self.timeout
        deadline = (None if (t is None or not math.isfinite(t))
                    else self._clock() + t)
        interval = 0.02
        while True:
            val = self._req(_CMD_GET, key)
            if val is not None:
                return val
            if deadline is not None and self._clock() >= deadline:
                raise TimeoutError(f"TCPStore.wait({key!r}) timed out")
            time.sleep(interval)
            interval = min(interval * 1.5, 0.5)

    def delete_key(self, key):
        self._req(_CMD_DEL, key)

    def ping(self):
        return self._req(_CMD_PING, "") == b"pong"

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._server is not None:
            self._server.stop()
            self._server = None
        if self._native_handle is not None:
            from ..core import native

            native.LIB.pt_store_server_stop(self._native_handle)
            self._native_handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
