"""Measured trials for the auto-tuner (the reference's whole point:
``distributed/auto_tuner/tuner.py:21`` searches over *measured* runs, not
model estimates).

`build_trial_runner` returns a run_fn that builds a real hybrid-parallel
training step for a candidate layout on the local device mesh, times a few
steps, and reads the XLA buffer-assignment stats for the compiled program.
`AutoTuner.measure()` drives it over the top-k predicted candidates and
re-ranks by what was actually observed, recording measured-vs-predicted
calibration ratios.

Trial model shapes come straight from ModelCfg — callers tuning on the
8-device CPU mesh pass a shrunken proxy model (the reference's trials run
the real model on the real cluster; a virtual CPU mesh can't, so the
calibration transfers the *ranking*, not absolute numbers).
"""
from __future__ import annotations

import time

__all__ = ["build_trial_runner", "TrialResult"]


class TrialResult(float):
    """Throughput metric (tokens/sec) carrying the measurement details."""

    def __new__(cls, tokens_per_sec, details):
        obj = super().__new__(cls, tokens_per_sec)
        obj.details = details
        return obj


def _gpt_config_from(model, cfg, recompute_policy="full"):
    from ...models.gpt import GPTConfig

    return GPTConfig(
        vocab_size=model.vocab_size,
        hidden_size=model.hidden_size,
        num_layers=model.num_layers,
        num_heads=model.num_attention_heads,
        intermediate_size=model.intermediate_size or None,
        max_seq_len=model.seq_length,
        dropout=0.0,
        recompute=cfg.recompute != "none",
        recompute_policy={"none": "full", "attn": "attn",
                          "full": "full"}[cfg.recompute],
        pp_interleave=cfg.vpp,
    )


def build_trial_runner(model, steps=3, seq_len=None):
    """run_fn(cfg) -> TrialResult(tokens/sec) for AutoTuner.tune/measure.

    Supports dp/sharding(+stage)/pp/micro_batch/recompute/vpp on the
    flagship stacked-decoder model; mp>1 additionally requires pp==1 (the
    TP trial uses explicit tensor-parallel layers). Unsupported combos
    raise ValueError — the tuner records them as failed trials.
    """
    import numpy as np

    def run(cfg):
        import jax

        import paddle_tpu as paddle
        from .. import fleet
        from ..parallel_step import ShardedTrainStep

        world = cfg.degree()
        if world > len(jax.devices()):
            raise ValueError(
                f"candidate degree {world} exceeds {len(jax.devices())} devices")
        if cfg.mp > 1 and cfg.pp > 1:
            raise ValueError("trial runner measures mp with pp==1 only")

        s = seq_len or model.seq_length
        b = cfg.micro_batch * cfg.dp * cfg.sharding

        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": cfg.dp, "mp_degree": cfg.mp, "pp_degree": cfg.pp,
            "sharding_degree": cfg.sharding,
        }
        fleet.init(is_collective=True, strategy=strategy)
        mesh = fleet.get_fleet_mesh()

        if cfg.mp > 1:
            trial = _build_tp_model(model, cfg)
        else:
            from ...models.gpt import GPTForCausalLMPipe

            gcfg = _gpt_config_from(model, cfg)
            trial = GPTForCausalLMPipe(gcfg)
            if cfg.pp > 1:
                trial.decoder.apply_pipeline_placements()
        if model.bytes_per_param == 2:
            # honor the declared training dtype: ModelCfg promises bf16
            # (bytes_per_param=2) but layers initialise f32 — an f32
            # trial of a bench-scale model carries ~2.7x the optimizer+
            # param bytes the memory model predicts and OOMs the chip
            # the real (bf16) config fits on (r4 calibration finding)
            for _, p in trial.named_parameters():
                p._data = p._data.astype(jax.numpy.bfloat16)

        opt = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=trial.parameters())
        step = ShardedTrainStep(
            trial, lambda i, l: trial.loss(i, l), opt, mesh,
            shard_opt_states=cfg.sharding > 1 and cfg.sharding_stage >= 1)

        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(0, model.vocab_size, (b, s)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.integers(0, model.vocab_size, (b, s)).astype(np.int64))

        _ = float(step(ids, labels).numpy())  # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids, labels)
        _ = float(loss.numpy())
        dt = (time.perf_counter() - t0) / steps

        mem = step.memory_stats(ids, labels)
        result = TrialResult(b * s / dt, {
            "step_ms": dt * 1e3,
            "peak_bytes": mem["peak_bytes"],
            "argument_bytes": mem["argument_bytes"],
            "temp_bytes": mem["temp_bytes"],
        })
        # free this trial's params/opt state before the NEXT candidate
        # compiles: back-to-back bench-scale trials otherwise stack two
        # models' HBM and OOM a config that fits alone (r4 calibration)
        import gc

        del step, opt, trial, ids, labels, loss
        gc.collect()
        return result

    return run


def _build_tp_model(model, cfg):
    """Tensor-parallel trial tower: TP layers carry real mp placements."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from .. import fleet

    h = model.hidden_size
    m = model.intermediate_size or 4 * h
    V = model.vocab_size

    class _Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.norm = nn.RMSNorm(h)
            self.up = fleet.ColumnParallelLinear(h, m, gather_output=False,
                                                 has_bias=False)
            self.down = fleet.RowParallelLinear(m, h, input_is_parallel=True,
                                                has_bias=False)

        def forward(self, x):
            import paddle_tpu.nn.functional as F

            return x + self.down(F.silu(self.up(self.norm(x))))

    class _Tower(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = fleet.VocabParallelEmbedding(V, h)
            self.blocks = nn.LayerList(
                [_Block() for _ in range(model.num_layers)])
            self.head = fleet.ColumnParallelLinear(
                h, V, gather_output=True, has_bias=False)

        def forward(self, ids):
            x = self.embed(ids)
            for blk in self.blocks:
                x = blk(x)
            return self.head(x)

        def loss(self, ids, labels):
            import paddle_tpu.nn.functional as F

            logits = self(ids)
            return F.cross_entropy(
                logits.reshape([-1, V]), labels.reshape([-1]))

    return _Tower()
