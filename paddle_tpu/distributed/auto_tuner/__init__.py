"""Auto-tuner: search over hybrid-parallel configs with pruning + cost model.

Parity: `python/paddle/distributed/auto_tuner/` — tuner.py:21 AutoTuner,
search.py grid search, prune.py's @register_prune rule registry
(prune_by_mp/pp/mbs/sharding/memory_estimation + history variants),
memory_cost_model.py get_model_memory_usage, recorder.py. The reference
drives real training trials per candidate; the loop here is the same
measure-and-pick, but the static models are TPU-flavored:

- memory model: transformer param count, ZeRO-stage-aware optimizer
  state sharding, activation bytes under none/attn/full rematerialisation
  (jax.checkpoint policies), vpp weight duplication ratio — against HBM
  per chip (v5e 16GB / v5p 95GB).
- cost model: per-chip FLOPs vs MXU throughput + TP allreduce bytes over
  ICI + the pipeline bubble factor (pp-1)/(m*vpp) — a roofline ranking
  so trials start from the most promising candidate, which is how the
  reference's `search_algo: grid -> prune -> cost-model sort` behaves.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field

__all__ = [
    "TunerCfg",
    "ModelCfg",
    "AutoTuner",
    "generate_candidates",
    "estimate_memory_gb",
    "estimate_step_time_ms",
    "prune_by_memory",
    "register_prune",
    "PRUNE_RULES",
]


@dataclass
class TunerCfg:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sharding: int = 1
    micro_batch: int = 1
    vpp: int = 1
    sharding_stage: int = 1          # ZeRO 1/2/3
    recompute: str = "none"          # none | attn | full

    def degree(self):
        return self.dp * self.mp * self.pp * self.sharding

    def to_dict(self):
        return dict(dp_degree=self.dp, mp_degree=self.mp, pp_degree=self.pp,
                    sharding_degree=self.sharding,
                    micro_batch_size=self.micro_batch,
                    vpp_degree=self.vpp, sharding_stage=self.sharding_stage,
                    recompute=self.recompute)


@dataclass
class ModelCfg:
    """Model + hardware description for the static models (the reference's
    tuner_cfg["model_cfg"] block)."""
    hidden_size: int = 4096
    num_layers: int = 32
    num_attention_heads: int = 32
    vocab_size: int = 32000
    seq_length: int = 2048
    intermediate_size: int = 0       # 0 -> 4h
    global_batch_size: int = 256
    bytes_per_param: int = 2         # bf16
    hbm_gb: float = 95.0             # v5p default
    mxu_tflops: float = 459.0        # v5p bf16 peak
    ici_gbps: float = 90.0           # per-link bidirectional-ish
    params_b: float = 0.0            # explicit param count override
    multi_precision: bool = False    # fp32 moments + master (12 B/param)

    @property
    def ffn(self):
        return self.intermediate_size or 4 * self.hidden_size

    def param_count(self):
        """Transformer params: embeddings + L * (attn 4h^2 + mlp 2*h*ffn +
        norms); `params_b` overrides when the model isn't transformer-shaped."""
        if self.params_b:
            return self.params_b
        h, L, V = self.hidden_size, self.num_layers, self.vocab_size
        per_layer = 4 * h * h + 2 * h * self.ffn + 4 * h
        return V * h + L * per_layer + h


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


# ---------------------------------------------------------------------------
# memory model (memory_cost_model.py:86 get_model_memory_usage analogue)
# ---------------------------------------------------------------------------
def estimate_memory_gb(cfg: TunerCfg, model: ModelCfg):
    """Per-chip peak memory: params + grads + optimizer states (placement
    by ZeRO stage) + activations (remat-dependent) + vpp duplication."""
    P = model.param_count()
    h, L, s = model.hidden_size, model.num_layers, model.seq_length
    b = cfg.micro_batch
    bpp = model.bytes_per_param

    model_shard = cfg.mp * cfg.pp                 # params always split by tp/pp
    # grads follow params; ZeRO-2/3 additionally shard grads; ZeRO-3 params
    grad_shard = model_shard * (cfg.sharding if cfg.sharding_stage >= 2 else 1)
    param_shard = model_shard * (cfg.sharding if cfg.sharding_stage >= 3 else 1)
    params = P * bpp / param_shard
    grads = P * bpp / grad_shard
    # adam: two moments in the PARAM dtype (the framework's default —
    # optimizer.py _init_slots keeps moments in p.dtype; fp32 moments +
    # master only under multi_precision). The old fixed 12-bytes/param
    # assumption predicted >=20.6GB for EVERY single-chip 1.3B config
    # and pruned them all, while the real bench runs at ~14.5GB — the
    # exact class of model bug the bench-scale calibration run exists to
    # catch (docs/TUNER_CALIBRATION.md, r4).
    opt_bpp = (12 if getattr(model, "multi_precision", False)
               else 2 * bpp)
    opt = P * opt_bpp / (model_shard * cfg.sharding)

    # activations per layer per microbatch (bf16):
    # none: ~ s*b*h*(34 + 5*a*s/h) (Megatron formula, attn scores incl.)
    # attn: attention internals recomputed -> ~ s*b*h*34
    # full: only layer boundaries saved -> ~ s*b*h*2
    a = model.num_attention_heads
    # the Megatron activation count is in UNITS OF ELEMENTS scaled for
    # 2-byte activations; activations are stored in the training dtype
    sb_h = s * b * h * (bpp / 2)
    if cfg.recompute == "full":
        act_per_layer = 2 * sb_h
    elif cfg.recompute == "attn":
        act_per_layer = 34 * sb_h
    else:
        act_per_layer = 34 * sb_h + 5 * a * s * s * b * (bpp / 2)
    # layers resident per chip; vpp interleave holds (1 + (pp-1)/(pp*vpp))
    # extra in-flight microbatch activations (pipeline_zero_bubble.py ratio)
    layers_local = max(L // cfg.pp, 1)
    vpp_ratio = 1.0 if cfg.pp == 1 else 1.0 + (cfg.pp - 1) / (cfg.pp * cfg.vpp)
    # pp keeps up to pp in-flight microbatches of the first stage's acts
    inflight = min(cfg.pp, max(model.global_batch_size
                               // (cfg.dp * cfg.sharding * b), 1))
    acts = act_per_layer * layers_local / cfg.mp * vpp_ratio * inflight

    return (params + grads + opt + acts) / 1e9


# ---------------------------------------------------------------------------
# cost model (roofline ranking; cost_model.py get_mem + sorting analogue)
# ---------------------------------------------------------------------------
def estimate_step_time_ms(cfg: TunerCfg, model: ModelCfg):
    """Rank candidates: compute time on the MXU + TP collectives over ICI
    + pipeline bubble. Absolute numbers are rough; the ORDER is what the
    tuner uses (best-first trial schedule)."""
    P = model.param_count()
    gbs, s = model.global_batch_size, model.seq_length
    data_world = cfg.dp * cfg.sharding
    if gbs % data_world:
        return float("inf")
    local_batch = gbs // data_world
    m = max(local_batch // cfg.micro_batch, 1)   # microbatches in flight

    # compute: 6*P*tokens flops for fwd+bwd, split over mp*pp
    tokens_local = local_batch * s
    flops = 6.0 * P * tokens_local
    if cfg.recompute == "full":
        flops *= 4.0 / 3.0                        # extra forward
    elif cfg.recompute == "attn":
        flops *= 1.15
    compute_ms = flops / (cfg.mp * cfg.pp) / (model.mxu_tflops * 1e12) * 1e3

    # TP comm: 4 allreduces of s*b*h bytes per layer per microbatch,
    # ring cost 2*(mp-1)/mp
    comm_ms = 0.0
    if cfg.mp > 1:
        bytes_tp = (4 * model.num_layers // cfg.pp) * m * (
            s * cfg.micro_batch * model.hidden_size * model.bytes_per_param)
        comm_ms += bytes_tp * 2 * (cfg.mp - 1) / cfg.mp / (
            model.ici_gbps * 1e9) * 1e3
    # dp/sharding grad sync: 2 bytes * P / (mp*pp), ring over data axis
    if data_world > 1:
        bytes_dp = P * model.bytes_per_param / (cfg.mp * cfg.pp)
        comm_ms += bytes_dp * 2 * (data_world - 1) / data_world / (
            model.ici_gbps * 1e9) * 1e3

    # pipeline bubble: (pp-1)/(m*vpp) of the compute is idle
    bubble = (cfg.pp - 1) / max(m * cfg.vpp, 1) if cfg.pp > 1 else 0.0
    return (compute_ms + comm_ms) * (1.0 + bubble)


# ---------------------------------------------------------------------------
# prune rules (prune.py's @register_prune registry)
# ---------------------------------------------------------------------------
PRUNE_RULES = []


def register_prune(fn):
    """A rule returns True to PRUNE `cfg`. Signature (cfg, model, history)."""
    PRUNE_RULES.append(fn)
    return fn


@register_prune
def prune_by_mp(cfg, model, history):
    """prune.py:129 — mp must divide heads and hidden; mp>hidden invalid."""
    return (model.num_attention_heads % cfg.mp != 0
            or model.hidden_size % cfg.mp != 0)


@register_prune
def prune_by_pp(cfg, model, history):
    """prune.py:173 — layers must divide into pp stages."""
    return model.num_layers % cfg.pp != 0


@register_prune
def prune_by_vpp(cfg, model, history):
    """prune.py:234 — layers/pp must divide vpp; vpp>1 needs pp>2."""
    if cfg.vpp == 1:
        return False
    if cfg.pp <= 2:
        return True
    return (model.num_layers // cfg.pp) % cfg.vpp != 0


@register_prune
def prune_by_mbs(cfg, model, history):
    """prune.py:307 — gbs divisible down to microbatches."""
    data_world = cfg.dp * cfg.sharding
    if model.global_batch_size % data_world != 0:
        return True
    return (model.global_batch_size // data_world) % cfg.micro_batch != 0


@register_prune
def prune_by_sharding(cfg, model, history):
    """prune.py:395 — stage>1 needs sharding degree>1 to mean anything."""
    return cfg.sharding == 1 and cfg.sharding_stage > 1


@register_prune
def prune_by_memory_estimation(cfg, model, history):
    """prune.py:605 — static OOM check against per-chip HBM."""
    return estimate_memory_gb(cfg, model) > model.hbm_gb


@register_prune
def prune_by_mbs_history(cfg, model, history):
    """prune.py:361 — if a no-heavier config with the same layout OOMed
    (metric None), this one will too. "No heavier" must hold on every
    memory axis: micro_batch, remat, ZeRO stage, and vpp (higher stage /
    vpp / remat all REDUCE memory, so the OOMed config must have had
    >= values there and <= micro_batch)."""
    for prev, metric in history:
        if metric is None and (
            prev.dp, prev.mp, prev.pp, prev.sharding) == (
            cfg.dp, cfg.mp, cfg.pp, cfg.sharding
        ) and prev.micro_batch <= cfg.micro_batch and (
            _remat_rank(prev.recompute) >= _remat_rank(cfg.recompute)
        ) and prev.sharding_stage >= cfg.sharding_stage and (
            prev.vpp >= cfg.vpp
        ):
            return True
    return False


def _remat_rank(r):
    return {"none": 0, "attn": 1, "full": 2}[r]


# ---------------------------------------------------------------------------
# candidate generation (search.py grid)
# ---------------------------------------------------------------------------
def generate_candidates(world_size, model: ModelCfg = None, global_batch=None,
                        max_mp=None, max_pp=None, tune_recompute=False):
    """All (dp, mp, pp, sharding, mbs[, vpp, recompute]) filling exactly
    `world_size` chips, pre-divisibility only (rules prune the rest)."""
    if model is not None and global_batch is None:
        global_batch = model.global_batch_size
    out = []
    for mp in _divisors(world_size):
        if max_mp and mp > max_mp:
            continue
        for pp in _divisors(world_size // mp):
            if max_pp and pp > max_pp:
                continue
            rest = world_size // (mp * pp)
            for sharding in _divisors(rest):
                dp = rest // sharding
                mbs_opts = [1, 2, 4, 8]
                if global_batch:
                    per = global_batch // max(dp * sharding, 1)
                    mbs_opts = [m for m in mbs_opts if per and per % m == 0]
                vpps = [1] if pp <= 2 else [1, 2]
                remats = (["none", "attn", "full"] if tune_recompute
                          else ["none"])
                stages = [1] if sharding == 1 else [1, 2, 3]
                for mbs, vpp, remat, stage in itertools.product(
                        mbs_opts or [1], vpps, remats, stages):
                    out.append(TunerCfg(dp, mp, pp, sharding, mbs, vpp,
                                        stage, remat))
    return out


def prune_by_memory(candidates, model_params_b=None, hbm_gb=95, model=None,
                    **model_kw):
    """Filter by the memory model. Accepts either a ModelCfg (budget =
    model.hbm_gb) or the legacy round-1 keywords (model_params_b plus
    hidden/layers/seq/bytes_per_param)."""
    if model is None:
        legacy_names = {"hidden": "hidden_size", "layers": "num_layers",
                        "seq": "seq_length"}
        kw = {legacy_names.get(k, k): v for k, v in model_kw.items()}
        model = ModelCfg(hbm_gb=hbm_gb, **kw)
        if model_params_b is not None:
            model.params_b = float(model_params_b)
    return [c for c in candidates
            if estimate_memory_gb(c, model) < model.hbm_gb]


# ---------------------------------------------------------------------------
# tuner (tuner.py:21)
# ---------------------------------------------------------------------------
class AutoTuner:
    """Grid -> prune (rule registry) -> cost-model sort -> measure loop.

    tuner_cfg keys (reference naming): world_size, model_cfg (dict for
    ModelCfg), max_mp_degree, max_pp_degree, tune_recompute,
    max_time_per_task. A run_fn returning None marks the trial OOM/failed
    (feeds the history prune rules); higher metric = better.
    """

    def __init__(self, tuner_cfg: dict):
        self.cfg = tuner_cfg
        world = tuner_cfg.get("world_size", 8)
        mc = dict(tuner_cfg.get("model_cfg", {}))
        # legacy round-1 keys
        if "hbm_gb" in tuner_cfg:
            mc.setdefault("hbm_gb", tuner_cfg["hbm_gb"])
        if "global_batch_size" in tuner_cfg:
            mc.setdefault("global_batch_size", tuner_cfg["global_batch_size"])
        self.model = ModelCfg(**mc)
        cands = generate_candidates(
            world, self.model,
            max_mp=tuner_cfg.get("max_mp_degree"),
            max_pp=tuner_cfg.get("max_pp_degree"),
            tune_recompute=tuner_cfg.get("tune_recompute", False),
        )
        self.history = []
        self.pruned = []
        cands = [c for c in cands if not self._pruned_static(c)]
        # best-first trial order by the cost model
        cands.sort(key=lambda c: estimate_step_time_ms(c, self.model))
        self.candidates = cands
        self._idx = 0

    def _pruned_static(self, cfg):
        for rule in PRUNE_RULES:
            if rule.__name__.endswith("_history"):
                continue
            if rule(cfg, self.model, self.history):
                self.pruned.append((cfg, rule.__name__))
                return True
        return False

    def _pruned_history(self, cfg):
        for rule in PRUNE_RULES:
            if not rule.__name__.endswith("_history"):
                continue
            if rule(cfg, self.model, self.history):
                self.pruned.append((cfg, rule.__name__))
                return True
        return False

    def search_once(self):
        """Next untried, not-history-pruned candidate (None = exhausted)."""
        while self._idx < len(self.candidates):
            cfg = self.candidates[self._idx]
            self._idx += 1
            if not self._pruned_history(cfg):
                return cfg
        return None

    def add_cfg(self, cfg: TunerCfg, metric):
        """metric None = OOM/failure (feeds history prunes)."""
        self.history.append((cfg, metric))

    def get_best_cfg(self):
        scored = [(c, m) for c, m in self.history if m is not None]
        if not scored:
            return None
        return max(scored, key=lambda kv: kv[1])[0]

    def tune(self, run_fn, max_trials=None):
        """Measure candidates best-predicted-first; returns the best."""
        trials = 0
        while True:
            if max_trials is not None and trials >= max_trials:
                break
            cfg = self.search_once()
            if cfg is None:
                break
            try:
                metric = run_fn(cfg)
            except Exception:
                metric = None
            self.add_cfg(cfg, metric)
            trials += 1
        return self.get_best_cfg()

    def measure(self, top_k=3, steps=3, run_fn=None, seq_len=None):
        """Run the top-k *predicted* candidates for real and re-rank.

        The reference tuner's core loop is search-over-measured-runs
        (tuner.py:21); the static roofline above only orders the trial
        schedule. This executes the built-in trial runner (hybrid-parallel
        step on the local device mesh — see measure.py) for each of the
        first `top_k` surviving candidates, records measured step time and
        XLA buffer-assignment memory, and re-ranks by measured throughput.

        Populates ``self.calibration``: one dict per measured candidate
        with predicted_ms / measured_ms / predicted_gb / measured_gb and
        the time_ratio, memory_ratio columns — the measured-vs-predicted
        record the static models can be sanity-checked against.

        Returns (best_cfg, ranked) where ranked is the measured ordering
        [(cfg, tokens_per_sec), ...] best first.
        """
        if run_fn is None:
            from .measure import build_trial_runner

            run_fn = build_trial_runner(self.model, steps=steps,
                                        seq_len=seq_len)
        self.calibration = []
        measured = []
        trials = 0
        while trials < top_k:
            cfg = self.search_once()
            if cfg is None:
                break
            try:
                metric = run_fn(cfg)
            except Exception:
                metric = None
            self.add_cfg(cfg, metric)
            if metric is not None:
                row = {
                    "cfg": cfg,
                    "predicted_ms": estimate_step_time_ms(cfg, self.model),
                    "predicted_gb": estimate_memory_gb(cfg, self.model),
                    "tokens_per_sec": float(metric),
                }
                details = getattr(metric, "details", None)
                if details:
                    row["measured_ms"] = details["step_ms"]
                    row["measured_gb"] = details["peak_bytes"] / 1e9
                    row["time_ratio"] = row["measured_ms"] / max(
                        row["predicted_ms"], 1e-9)
                    row["memory_ratio"] = row["measured_gb"] / max(
                        row["predicted_gb"], 1e-9)
                self.calibration.append(row)
                measured.append((cfg, float(metric)))
            trials += 1
        measured.sort(key=lambda kv: -kv[1])
        best = measured[0][0] if measured else None
        return best, measured
