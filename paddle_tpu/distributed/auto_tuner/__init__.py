"""Auto-tuner: black-box search over hybrid-parallel configs.

Parity: `python/paddle/distributed/auto_tuner/` (tuner.py:21 AutoTuner,
search.py grid search, prune.py constraint pruning). Searches
(dp, mp, pp, sharding, micro_batch) combinations for a world size, prunes
infeasible ones with a memory model, and ranks candidates by a
user-supplied run function (throughput) — the same measure-and-pick loop
the reference drives with real training trials.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field


@dataclass
class TunerCfg:
    dp: int
    mp: int
    pp: int
    sharding: int
    micro_batch: int

    def degree(self):
        return self.dp * self.mp * self.pp * self.sharding

    def to_dict(self):
        return dict(dp_degree=self.dp, mp_degree=self.mp, pp_degree=self.pp,
                    sharding_degree=self.sharding,
                    micro_batch_size=self.micro_batch)


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def generate_candidates(world_size, global_batch=None, max_mp=None,
                        max_pp=None):
    """All (dp, mp, pp, sharding, mbs) filling exactly `world_size`."""
    out = []
    for mp in _divisors(world_size):
        if max_mp and mp > max_mp:
            continue
        for pp in _divisors(world_size // mp):
            if max_pp and pp > max_pp:
                continue
            rest = world_size // (mp * pp)
            for sharding in _divisors(rest):
                dp = rest // sharding
                mbs_opts = [1, 2, 4, 8]
                if global_batch:
                    per = global_batch // max(dp * sharding, 1)
                    mbs_opts = [m for m in mbs_opts if per and per % m == 0]
                for mbs in (mbs_opts or [1]):
                    out.append(TunerCfg(dp, mp, pp, sharding, mbs))
    return out


def estimate_memory_gb(cfg: TunerCfg, model_params_b, hidden=4096,
                       layers=32, seq=2048, bytes_per_param=2):
    """Coarse per-chip memory model (prune.py analogue): params + grads +
    optimizer states (sharded) + activations (mp/pp/microbatch split)."""
    shard_factor = cfg.mp * cfg.pp * cfg.sharding
    param_gb = model_params_b * bytes_per_param / shard_factor / 1e9
    grad_gb = param_gb
    # adam moments in fp32
    opt_gb = model_params_b * 8 / (cfg.mp * cfg.pp * cfg.sharding) / 1e9
    act_gb = (cfg.micro_batch * seq * hidden * layers * 2 * 12
              / (cfg.mp * cfg.pp)) / 1e9
    return param_gb + grad_gb + opt_gb + act_gb


def prune_by_memory(candidates, model_params_b, hbm_gb=95, **model_kw):
    return [c for c in candidates
            if estimate_memory_gb(c, model_params_b, **model_kw) < hbm_gb]


class AutoTuner:
    """parity: auto_tuner/tuner.py:21."""

    def __init__(self, tuner_cfg: dict):
        self.cfg = tuner_cfg
        world = tuner_cfg.get("world_size", 8)
        cands = generate_candidates(
            world,
            global_batch=tuner_cfg.get("global_batch_size"),
            max_mp=tuner_cfg.get("max_mp_degree"),
            max_pp=tuner_cfg.get("max_pp_degree"),
        )
        params_b = tuner_cfg.get("model_params_b")
        if params_b:
            cands = prune_by_memory(
                cands, params_b, hbm_gb=tuner_cfg.get("hbm_gb", 95))
        self.candidates = cands
        self.history = []
        self._it = iter(self.candidates)

    def search_once(self):
        """Next untried candidate or None when exhausted."""
        try:
            return next(self._it)
        except StopIteration:
            return None

    def add_cfg(self, cfg: TunerCfg, metric: float):
        self.history.append((cfg, metric))

    def get_best_cfg(self):
        if not self.history:
            return None
        return max(self.history, key=lambda kv: kv[1])[0]

    def tune(self, run_fn, max_trials=None):
        """Measure each candidate with run_fn(cfg) -> throughput; returns
        the best config."""
        for i, cfg in enumerate(self.candidates):
            if max_trials is not None and i >= max_trials:
                break
            try:
                metric = run_fn(cfg)
            except Exception:
                metric = float("-inf")
            self.add_cfg(cfg, metric)
        return self.get_best_cfg()
