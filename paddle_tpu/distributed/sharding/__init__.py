"""paddle.distributed.sharding (parity: group_sharded_parallel API)."""
from ..parallel_step import group_sharded_parallel  # noqa: F401

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model (gathers full values; parity:
    sharding/group_sharded.py save_group_sharded_model)."""
    import os

    import paddle_tpu as paddle

    os.makedirs(output, exist_ok=True)
    paddle.save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        paddle.save(optimizer.state_dict(),
                    os.path.join(output, "model.pdopt"))
