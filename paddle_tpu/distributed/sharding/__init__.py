"""paddle.distributed.sharding (parity: group_sharded_parallel API)."""
from ..parallel_step import group_sharded_parallel  # noqa: F401

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def save_group_sharded_model(model, output, optimizer=None):
    """Save a group-sharded model root (parity slot:
    sharding/group_sharded.py save_group_sharded_model).

    Routed through :class:`CheckpointManager` (docs/ZERO.md checkpoint
    contract): the old path pulled FULL values through ``state_dict()``
    on every rank and pickled them — on a stage-3 root that all-gathers
    every sharded param/slot onto every host, world-size times. The
    manager's sharded writer instead saves each dp-sharded param and
    optimizer slot as per-shard boxes with global metadata (only the
    coordinator writes metadata + COMMIT), and restores reshard-on-load
    across topology changes. ``tools/ckpt_inspect.py`` validates the
    resulting root; restore with
    ``CheckpointManager(output).restore_training_state(model, opt)``.
    """
    from ..checkpoint.manager import CheckpointManager

    manager = CheckpointManager(output)
    try:
        manager.save_training_state(0, model, optimizer)
    finally:
        manager.close()
    return output
