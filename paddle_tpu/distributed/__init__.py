"""paddle.distributed — the TPU-native distributed stack.

Design (SURVEY §5 "Distributed communication backend"): no NCCL — the
device mesh is the communicator. Collectives compile to XLA ops over
ICI/DCN; process bootstrap is multi-controller jax.distributed; hybrid
parallelism is a ProcessMesh with axes (pp, dp, sharding, sep, mp); the
reference's ProcessGroup/comm-context/watchdog machinery
(`process_group.h:48`, `comm_task_manager.h:37`) has no equivalent because
compiled collectives cannot desynchronize — XLA sequences them.

Submodules: `communication` (collective API), `auto_parallel` (DistTensor/
ProcessMesh/shard_tensor/reshard), `fleet` (hybrid parallel),
`parallel_step` (the compiled hybrid train step).
"""
from __future__ import annotations

import os

from .communication import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    destroy_process_group,
    gather,
    get_group,
    get_rank,
    get_world_size,
    is_available,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
    wait,
)
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    TensorDistAttr,
    dtensor_from_fn,
    dtensor_from_local,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    shard_activation,
)
from .parallel_step import (  # noqa: F401
    ShardedTrainStep,
    group_sharded_parallel,
    shard_model_parameters,
)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
from .context_parallel import (  # noqa: F401
    context_parallel_attention,
    ring_attention,
    ulysses_attention,
)

# fleet.mpu split op lives at paddle.distributed.split in the reference
from .fleet.mpu import split  # noqa: F401


_initialized = [False]


def is_initialized():
    return _initialized[0]


def init_parallel_env():
    """Multi-controller bootstrap over jax.distributed (parallel.py:978).

    Env contract matches the reference launcher: PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, PADDLE_MASTER. Single process: no-op."""
    if _initialized[0]:
        return
    world = get_world_size()
    if world > 1 and "PADDLE_MASTER" in os.environ:
        import jax

        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_MASTER"],
            num_processes=world,
            process_id=get_rank(),
        )
    _initialized[0] = True
    return ParallelEnv()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", 0))

    @property
    def dev_id(self):
        return self.device_id


def _spawn_target(func, args, rank, nprocs, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, **options):
    """paddle.distributed.spawn parity (spawn.py:456).

    TPU note: one jax process drives all local chips, so the SPMD program
    already covers every device — nprocs<=1 runs func inline. nprocs>1
    starts real OS processes with the PADDLE_* env contract (multi-host
    style; mainly the CPU fake-backend test path).
    """
    if nprocs is None or nprocs <= 1:
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    env = {k: v for k, v in os.environ.items() if k.startswith(("PADDLE_", "FLAGS_"))}
    procs = [
        ctx.Process(target=_spawn_target, args=(func, args, r, nprocs, env))
        for r in range(nprocs)
    ]
    for p in procs:
        p.start()
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawn: child exit codes {bad}")
    return procs


def get_backend():
    return "xla"

from . import auto_tuner  # noqa: E402,F401
