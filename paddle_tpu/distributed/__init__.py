"""paddle.distributed — the TPU-native distributed stack.

Design (SURVEY §5 "Distributed communication backend"): no NCCL — the
device mesh is the communicator. Collectives compile to XLA ops over
ICI/DCN; process bootstrap is multi-controller jax.distributed; hybrid
parallelism is a ProcessMesh with axes (pp, dp, sharding, sep, mp); the
reference's ProcessGroup/comm-context/watchdog machinery
(`process_group.h:48`, `comm_task_manager.h:37`) has no equivalent because
compiled collectives cannot desynchronize — XLA sequences them.

Submodules: `communication` (collective API), `auto_parallel` (DistTensor/
ProcessMesh/shard_tensor/reshard), `fleet` (hybrid parallel),
`parallel_step` (the compiled hybrid train step).
"""
from __future__ import annotations

import os

from .communication import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    barrier,
    broadcast,
    destroy_process_group,
    gather,
    get_group,
    get_rank,
    get_world_size,
    is_available,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
    wait,
)
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    TensorDistAttr,
    dtensor_from_fn,
    dtensor_from_local,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    shard_activation,
)
from .spmd_rules import (  # noqa: F401
    DistTensorSpec,
    get_spmd_rule,
    register_spmd_rule,
)
from .parallel_step import (  # noqa: F401
    ShardedTrainStep,
    group_sharded_parallel,
    shard_model_parameters,
)
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import (load_checkpoint, load_state_dict,  # noqa: F401
                         save_checkpoint, save_state_dict)
from .checkpoint.manager import (  # noqa: F401
    CheckpointManager,
    PreemptionGuard,
)
from .context_parallel import (  # noqa: F401
    context_parallel_attention,
    ring_attention,
    ulysses_attention,
)

# fleet.mpu split op lives at paddle.distributed.split in the reference
from .fleet.mpu import split  # noqa: F401


_initialized = [False]


def is_initialized():
    return _initialized[0]


def init_parallel_env():
    """Multi-controller bootstrap over jax.distributed (parallel.py:978).

    Env contract matches the reference launcher: PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, PADDLE_MASTER. Single process: no-op."""
    if _initialized[0]:
        return
    world = get_world_size()
    if world > 1 and "PADDLE_MASTER" in os.environ:
        import jax

        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_MASTER"],
            num_processes=world,
            process_id=get_rank(),
        )
        # no eager-p2p store here: with jax.distributed live, send/recv
        # compile to ppermute over the {src, dst} device pair; the TCPStore
        # mailbox tier only serves PADDLE_MASTER-without-jax.distributed
        # runs and starts lazily on first use
    _initialized[0] = True
    return ParallelEnv()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", 0))

    @property
    def dev_id(self):
        return self.device_id


def _spawn_target(func, args, rank, nprocs, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, **options):
    """paddle.distributed.spawn parity (spawn.py:456).

    TPU note: one jax process drives all local chips, so the SPMD program
    already covers every device — nprocs<=1 runs func inline. nprocs>1
    starts real OS processes with the PADDLE_* env contract (multi-host
    style; mainly the CPU fake-backend test path).
    """
    if nprocs is None or nprocs <= 1:
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    env = {k: v for k, v in os.environ.items() if k.startswith(("PADDLE_", "FLAGS_"))}
    procs = [
        ctx.Process(target=_spawn_target, args=(func, args, r, nprocs, env))
        for r in range(nprocs)
    ]
    for p in procs:
        p.start()
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawn: child exit codes {bad}")
    return procs


def get_backend():
    return "xla"

from . import auto_tuner  # noqa: E402,F401

from . import launch  # noqa: E402,F401
from . import rpc  # noqa: E402,F401


# -- remaining reference exports (parity: distributed/__init__.py __all__) --
class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


class ReduceType:
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


from .auto_parallel import TensorDistAttr as DistAttr  # noqa: E402,F401


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    res = all_to_all(out_tensor_list if isinstance(out_tensor_list, list)
                     else [], in_tensor_list, group=group)
    return res


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all: split dim 0 across ranks and exchange."""
    from .communication import _get_default_group, all_to_all as _a2a

    group = group or _get_default_group()
    parts = []
    n = group.nranks
    per = in_tensor.shape[0] // n
    chunks = [in_tensor[i * per:(i + 1) * per] for i in range(n)]
    out = []
    _a2a(out, chunks, group=group)
    import paddle_tpu as _p

    result = _p.concat(out, axis=0)
    if out_tensor is not None:
        out_tensor._data = result._data
        return out_tensor
    return result


def isend(tensor, dst=0, group=None):
    send(tensor, dst=dst, group=group, sync_op=False)
    return _ImmediateTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src=src, group=group, sync_op=False)
    return _ImmediateTask()


class _ImmediateTask:
    """Compiled collectives complete as part of the program; wait is a
    no-op (matching sync_op=False task semantics)."""

    def wait(self):
        return True

    def is_completed(self):
        return True


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Single-controller SPMD: every rank sees the same objects."""
    import copy

    from .communication import _get_default_group

    group = group or _get_default_group()
    idx = min(get_rank(), len(in_object_list or []) - 1)
    if in_object_list:
        out_object_list.append(copy.deepcopy(in_object_list[max(idx, 0)]))
    return out_object_list


def broadcast_object_list(object_list, src=0, group=None):
    return object_list  # replicated already under single-controller SPMD


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    pass


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None,
                     is_dataset_splitted=False, dense_tensor_idx=None):
    """parity: auto_parallel shard_dataloader — places each batch on the
    mesh with batch-dim sharding. The loader is wrapped so iterated
    tensors come out sharded."""
    from .auto_parallel import shard_tensor, Shard, Replicate

    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes

    class _ShardedLoader:
        def __init__(self, inner):
            self._inner = inner

        def __iter__(self):
            dim = shard_dims if isinstance(shard_dims, (int, str)) else 0
            for batch in self._inner:
                items = batch if isinstance(batch, (list, tuple)) else [batch]
                out = []
                for t in items:
                    try:
                        placements = [Replicate() for _ in mesh.dim_names]
                        ax = (mesh.dim_names.index(dim)
                              if isinstance(dim, str) else 0)
                        placements[ax] = Shard(0)
                        out.append(shard_tensor(t, mesh, placements))
                    except Exception:
                        out.append(t)
                yield out if isinstance(batch, (list, tuple)) else out[0]

        def __len__(self):
            return len(self._inner)

    return _ShardedLoader(dataloader)


def shard_scaler(scaler):
    return scaler  # found_inf is computed inside the compiled step


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """auto_parallel to_static -> DistModel-style wrapper: the layer's
    step is compiled over the active mesh by ShardedTrainStep."""
    from .auto_parallel import get_mesh
    from .parallel_step import ShardedTrainStep

    mesh = get_mesh()

    class DistModel:
        def __init__(self):
            self.network = layer
            self._step = None
            self._mode = "train"

        def train(self):
            self._mode = "train"

        def eval(self):
            self._mode = "eval"

        def __call__(self, *batch):
            if self._mode == "eval" or optimizer is None:
                out = layer(*batch[:-1])
                return loss(out, batch[-1]) if loss else out
            if self._step is None:
                def train_fn(*b):
                    out = layer(*b[:-1])
                    return loss(out, b[-1])

                self._step = ShardedTrainStep(layer, train_fn, optimizer,
                                              mesh)
            return self._step(*batch)

    return DistModel()


class ShardingStage1:
    def __init__(self, axis=None, mesh=None):
        self.axis, self.mesh = axis, mesh


class ShardingStage2(ShardingStage1):
    pass


class ShardingStage3(ShardingStage1):
    pass


# PS-era dataset entries (parameter-server capability slots; the TPU build
# trains dense models — these configure nothing but keep configs loadable)
class _PsEntry:
    def __init__(self, *args, **kwargs):
        self.args = args


class CountFilterEntry(_PsEntry):
    pass


class ShowClickEntry(_PsEntry):
    pass


class ProbabilityEntry(_PsEntry):
    pass


class QueueDataset:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "QueueDataset is parameter-server streaming IO; use paddle.io."
            "IterableDataset + DataLoader on TPU")


class InMemoryDataset(QueueDataset):
    pass


from . import io  # noqa: E402,F401


# -- intermediate auto-parallel API (parity: auto_parallel/intermediate) ----
class Strategy:
    """parity: auto_parallel Strategy config (api.py:1973)."""

    def __init__(self, config=None):
        cfg = config or {}
        self.sharding = type("C", (), dict(enable=False, degree=1, stage=1))()
        self.amp = type("C", (), dict(enable=False, dtype="bfloat16",
                                      level="O2"))()
        self.pipeline = type("C", (), dict(enable=False, schedule_mode="1F1B",
                                           micro_batch_size=1,
                                           accumulate_steps=1))()
        self.recompute = type("C", (), dict(enable=False))()
        self.gradient_merge = type("C", (), dict(enable=False, k_steps=1))()
        for k, v in cfg.items():
            setattr(self, k, v)


DistModel = None  # assigned by to_static at call time (object API below)


class LocalLayer:
    """parity: dist LocalLayer — runs a layer on local shards inside
    shard_map contexts; under GSPMD the wrapped layer simply executes."""

    def __init__(self, layer, out_dist_attrs=None):
        self.layer = layer

    def __call__(self, *args, **kwargs):
        return self.layer(*args, **kwargs)


def unshard_dtensor(dist_tensor):
    """Gather a DistTensor back to a replicated dense tensor."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from ..core.tensor import Tensor

    arr = dist_tensor._data
    if hasattr(arr, "sharding") and hasattr(arr.sharding, "mesh"):
        arr = jax.device_put(arr, NamedSharding(arr.sharding.mesh,
                                                PartitionSpec()))
    out = Tensor(arr)
    out.stop_gradient = dist_tensor.stop_gradient
    return out


# plan markers for the intermediate `parallelize` API
class _PlanMarker:
    def __init__(self, *args, **kwargs):
        self.args = args
        self.kwargs = kwargs


class ColWiseParallel(_PlanMarker):
    pass


class RowWiseParallel(_PlanMarker):
    pass


class SequenceParallelBegin(_PlanMarker):
    pass


class SequenceParallelEnd(_PlanMarker):
    pass


class SequenceParallelEnable(_PlanMarker):
    pass


class SequenceParallelDisable(_PlanMarker):
    pass


class PrepareLayerInput(_PlanMarker):
    pass


class PrepareLayerOutput(_PlanMarker):
    pass


class SplitPoint:
    BEGINNING = "beginning"
    END = "end"


def _match(name, pattern):
    import re

    return re.fullmatch(pattern.replace("*", ".*"), name) is not None


def _auto_mp_plan(model, example_inputs, axis_size):
    """Derive ColWise/RowWise markers from the per-op cost planner
    (VERDICT r3 item 9 — `plan_matmul_shardings` consumed, not admired).

    Traces the model forward, scores every top-level dot_general's
    classical placements (op_cost.plan_matmul_shardings), and maps each
    plan back to the Linear weight with matching (k, n) dims:
    split_n -> ColWiseParallel, split_k -> RowWiseParallel, else
    replicated. Mirrors the reference's planner-driven dist_attr
    completion (auto_parallel/static/tuner/)."""
    from .op_cost import plan_matmul_shardings

    def fn(*arrays):
        import paddle_tpu as _p

        outs = model(*[_p.Tensor(a) for a in arrays])
        from jax import tree_util as _tu

        return [t._data if hasattr(t, "_data") else t
                for t in _tu.tree_leaves(outs)]

    arrays = [x._data if hasattr(x, "_data") else x for x in example_inputs]
    plans = plan_matmul_shardings(fn, *arrays, axis_size=axis_size)
    # map plans to layers by EXECUTION ORDER within each (k, n) shape
    # class — same-shape weights (q/k/v/o projections are all [h, h])
    # must each get THEIR OWN matmul's placement, not the first one's
    remaining = list(plans)
    out = {}
    for lname, layer in model.named_sublayers():
        w = getattr(layer, "weight", None)
        if w is None or w._data.ndim != 2:
            continue
        shape = tuple(int(s) for s in w._data.shape)
        p = next((pl for pl in remaining if (pl.k, pl.n) == shape), None)
        if p is None:
            continue
        remaining.remove(p)
        if p.choice == "split_n":
            out[lname] = ColWiseParallel()
        elif p.choice == "split_k":
            out[lname] = RowWiseParallel()
    return out


def parallelize(model, optimizer=None, mesh=None, config=None):
    """parity: auto_parallel/intermediate/parallelize.py:51.

    Applies a plan dict {"mp_config": {"parallelize_plan": {name: marker}}}
    by marking matched Linear/Embedding weights with mp placements.
    With {"mp_config": {"auto": True, "example_inputs": [...]}} the plan
    is DERIVED from the per-op cost planner instead of written by hand.
    When the mesh has a pp axis > 1 and the model (or a submodule)
    exposes ``apply_pipeline_placements`` (the stacked-decoder family),
    stage placements are applied automatically — including TP over the
    "mp" axis when present — so ``parallelize(model)`` alone wires the
    full pp x mp x dp hybrid from the mesh shape (reference pp_config:
    intermediate/parallelize.py split_spec). dp needs no marking: the
    batch shards at the compiled step.
    """
    from .auto_parallel import Replicate, Shard, TensorDistAttr, get_mesh
    from .fleet import get_fleet_mesh

    # this is the auto-parallel intermediate API: an explicit set_mesh()
    # is ITS configuration surface and keeps precedence; the fleet mesh
    # is the fallback so a fleet-only init still wires pp below
    mesh = mesh or get_mesh() or get_fleet_mesh()
    config = config or {}
    pp_cfg = config.get("pp_config") or {}
    if (mesh is not None and "pp" in mesh.dim_names
            and mesh.get_dim_size("pp") > 1
            and pp_cfg.get("enable", True)):
        # tp_axis: "auto" (default) picks "mp" when present AND the
        # model's head/ffn dims divide it — falling back to stage-only
        # placements otherwise; an explicit None means stage-only
        tp_axis = pp_cfg.get("tp_axis", "auto")
        if tp_axis == "auto":
            tp_axis = ("mp" if "mp" in mesh.dim_names
                       and mesh.get_dim_size("mp") > 1 else None)
            tp_fallback = True
        else:
            tp_fallback = False
        for _, sub in [("", model)] + list(model.named_sublayers()):
            if hasattr(sub, "apply_pipeline_placements"):
                try:
                    sub.apply_pipeline_placements(mesh, tp_axis=tp_axis)
                except ValueError:
                    if not (tp_fallback and tp_axis is not None):
                        raise
                    sub.apply_pipeline_placements(mesh, tp_axis=None)
                break
    mp_cfg = config.get("mp_config") or {}
    plan = mp_cfg.get("parallelize_plan") or {}
    if (not plan and mp_cfg.get("auto") and mesh is not None
            and "mp" in mesh.dim_names):
        plan = _auto_mp_plan(model, mp_cfg.get("example_inputs") or [],
                             mesh.get_dim_size("mp"))
    if mesh is not None and "mp" in mesh.dim_names and plan:
        ax = mesh.dim_names.index("mp")
        for lname, layer in model.named_sublayers():
            for pattern, marker in plan.items():
                if not _match(lname, pattern):
                    continue
                w = getattr(layer, "weight", None)
                if w is None:
                    continue
                # MERGE with any placements already on the weight (e.g.
                # the pp Shard(0) applied above) — rebuilding from
                # all-Replicate would silently erase them. Compare meshes
                # by VALUE (shape + dim_names + device ids, ProcessMesh
                # __eq__): an equal-but-distinct mesh object must not
                # silently drop prior pp/TP placements (ADVICE round 5)
                if (w._dist_attr is not None
                        and w._dist_attr.process_mesh == mesh):
                    placements = list(w._dist_attr.placements)
                else:
                    placements = [Replicate() for _ in mesh.dim_names]
                if isinstance(marker, ColWiseParallel):
                    placements[ax] = Shard(w._data.ndim - 1)
                elif isinstance(marker, RowWiseParallel):
                    placements[ax] = Shard(0)
                else:
                    continue
                w._dist_attr = TensorDistAttr(mesh, placements)
    return model, optimizer


def to_distributed(model, optimizer, dataloader, device_num=None,
                   node_num=None, config=None):
    """parity: experimental to_distributed — returns the triple wired to
    the active mesh (ShardedTrainStep does placement at first step)."""
    return model, optimizer, dataloader


class P2POp:
    """parity: distributed.P2POp — a deferred send/recv description."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """parity: communication/batch_isend_irecv — under compiled SPMD the
    batched p2p pairs lower to one fused ppermute; eagerly each op runs
    through send/recv."""
    tasks = []
    for op in p2p_op_list:
        if op.op in (isend, "isend", send):
            tasks.append(isend(op.tensor, dst=op.peer, group=op.group))
        else:
            tasks.append(irecv(op.tensor, src=op.peer, group=op.group))
    return tasks


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    barrier(group)


def all_gather_into_tensor(output, input, group=None, sync_op=True):
    """Concat-form all_gather writing into a preallocated output tensor."""
    parts = []
    all_gather(parts, input, group=group)
    import paddle_tpu as _p

    result = _p.concat(parts, axis=0)
    output._data = result._data
    return output


from . import passes  # noqa: F401,E402
from . import sharding  # noqa: F401,E402

from . import op_cost  # noqa: F401  (per-op cost + planner)
