"""paddle.distributed — minimal bootstrap surface (full stack in progress).

The TPU-native distributed design (SURVEY.md §5): no NCCL — the device mesh
is the communicator. Collectives compile to XLA ops over ICI/DCN. This module
currently provides the process/env surface; the collective API, fleet hybrid
parallel, and auto_parallel land in paddle_tpu.distributed.* modules.
"""
from __future__ import annotations

import os


def get_rank(group=None):
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size(group=None):
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))


def is_initialized():
    return _initialized[0]


_initialized = [False]


def init_parallel_env():
    """Multi-controller bootstrap over jax.distributed (single-proc no-op)."""
    if _initialized[0]:
        return
    world = get_world_size()
    if world > 1 and "PADDLE_MASTER" in os.environ:
        import jax

        coord = os.environ["PADDLE_MASTER"]
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=world,
            process_id=get_rank(),
        )
    _initialized[0] = True


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return int(os.environ.get("FLAGS_selected_tpus", 0))
