"""paddle.distributed.communication.stream — stream-variant collectives.

The reference exposes per-stream versions (sync_op/use_calc_stream
control). Under XLA there is one ordered stream per device and
collectives are compiled, so these delegate to the standard API; the
returned task object carries the async-looking surface (`wait`)."""
from __future__ import annotations

from . import (  # noqa: F401
    all_gather,
    all_reduce,
    broadcast,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    gather,
)
from . import all_to_all as alltoall  # noqa: F401  (stream-module naming)


def alltoall_single(out_tensor, in_tensor, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    """Single-tensor all-to-all: splits along dim 0 (the reference's
    alltoall_single), built on the list-based all_to_all."""
    import paddle_tpu as paddle

    n = paddle.distributed.get_world_size(group)
    ins = list(paddle.split(in_tensor, in_split_sizes or n, axis=0))         if not isinstance(in_tensor, (list, tuple)) else list(in_tensor)
    outs = []  # all_to_all BUILDS the list (append)
    alltoall(outs, ins, group=group, sync_op=sync_op)
    result = paddle.concat(outs, axis=0)
    out_tensor._assign_result_(result) if hasattr(
        out_tensor, "_assign_result_") else None
    return result

__all__ = [
    "all_gather", "all_reduce", "alltoall", "alltoall_single", "broadcast",
    "reduce", "reduce_scatter", "recv", "scatter", "send", "gather",
]
