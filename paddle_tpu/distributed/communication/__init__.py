"""paddle.distributed collective API — XLA collectives, no NCCL.

Parity surface: `python/paddle/distributed/communication/*.py`
(all_reduce/all_gather/reduce_scatter/broadcast/all_to_all/send/recv/
scatter/gather/barrier) and `collective.py:194 new_group`.

TPU-native design (SURVEY §5 "Distributed communication backend"): the
device mesh IS the communicator. Each collective here is a tiny jit'd
`shard_map` program over the participating devices — XLA lowers psum /
all_gather / ppermute / all_to_all onto ICI/DCN. This replaces the whole
ProcessGroupNCCL stack (`process_group_nccl.cc`): no comm contexts, no
stream/task objects (XLA schedules), no watchdog (no hangs to watch —
collectives are compiled into the step program).

Eager semantics: the reference's eager collectives are SPMD — every rank
calls `all_reduce(local_tensor)`. Here a "rank" is a device in the group's
mesh. The eager path assembles the per-rank tensors into one stacked
global array over the group axis, runs the compiled collective, and hands
back this rank's view. Under a multi-controller deployment each process
contributes its local shard via `make_array_from_process_local_data`; in
single-controller tests all ranks live in one process (the reference tests
the same way via its fake custom_cpu backend, SURVEY §4).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from jax import shard_map

from ...core.tensor import Tensor
from ... import telemetry as _telemetry

P = PartitionSpec

# collective call/byte counters, labeled by op and the mesh axis the
# collective runs over (the eager group collectives all ride the group's
# 1-D "g" axis; compiled-step collectives are XLA-internal and show up in
# the profiler's device table instead). Calls are counted at API entry so
# degenerate single-rank calls are visible too — a dp=1 run that still
# pays per-step all_reduce python overhead is a real finding.
_TELEMETRY_REG = _telemetry.get_registry()
_COLL_CALLS = _telemetry.counter(
    "collective_calls_total", "eager collective API calls",
    labelnames=("op", "axis", "nranks"))
_COLL_BYTES = _telemetry.counter(
    "collective_bytes_total", "payload bytes entering eager collectives",
    labelnames=("op", "axis", "nranks"))
_COLL_SECONDS = _telemetry.histogram(
    "collective_seconds", "wall time per collective entry",
    labelnames=("op", "axis"))


def _note_collective(op, group, *tensors):
    """Count the call + payload bytes AND return a timer over the whole
    entry (``with _note_collective(...)``): the collective_seconds{op,
    axis} histogram next to the call/byte counters, so a snapshot shows
    where comm wall time went, not only how much traffic moved
    (docs/TELEMETRY.md)."""
    if not _TELEMETRY_REG.enabled:
        return _telemetry.timer(_COLL_SECONDS)  # disabled: no clock reads
    nranks = group.nranks if group is not None else 1
    labels = (op, "g", str(nranks))
    _COLL_CALLS.inc(labels=labels)
    nbytes = 0
    for t in tensors:
        data = getattr(t, "_data", t)
        nbytes += int(getattr(data, "nbytes", 0) or 0)
    if nbytes:
        _COLL_BYTES.inc(nbytes, labels=labels)
    return _telemetry.timer(_COLL_SECONDS, labels=(op, "g"))


# ---------------------------------------------------------------------------
# ReduceOp / groups
# ---------------------------------------------------------------------------
class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


# MIN/MAX ride pmin/pmax (true reductions — nothing to cap); PROD has no
# pprod primitive, so it is reduced pairwise (_prod_reducer below) instead
# of the old jnp.prod(all_gather(...)), which materialized an n-x copy of
# the tensor on every rank before reducing it.
_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
    ReduceOp.AVG: lambda x, ax: jax.lax.pmean(x, ax),
}


def _prod_reducer(n):
    """O(1)-memory cross-rank product: recursive doubling over XOR
    partners when ``n`` is a power of two (log2 n ppermutes), ring
    rotation otherwise (n-1 ppermutes) — at most two live copies of the
    tensor at any point, vs the gathered [n, ...] stack."""

    def red(x, ax):
        if n & (n - 1) == 0:
            d = 1
            while d < n:
                perm = [(i, i ^ d) for i in range(n)]
                x = x * jax.lax.ppermute(x, ax, perm)
                d *= 2
            return x
        acc, rot = x, x
        perm = [(i, (i + 1) % n) for i in range(n)]
        for _ in range(n - 1):
            rot = jax.lax.ppermute(rot, ax, perm)
            acc = acc * rot
        return acc

    return red


def _resolve_reducer(op, n):
    """The per-shard reduction body for ``op`` over an ``n``-rank axis —
    shared by every eager collective that accepts a ReduceOp, so none of
    them can silently fall back to SUM for the exotic ops."""
    if op in _REDUCERS:
        return _REDUCERS[op]
    if op == ReduceOp.PROD:
        return _prod_reducer(n)
    raise ValueError(f"unknown ReduceOp {op!r}")


# Compiled eager-collective programs, keyed (op, group, payload shape/
# dtype): the old path rebuilt + retraced a fresh shard_map closure on
# EVERY call (ISSUE 6 satellite — per-call Python overhead at eager
# entry). Steady-state calls now hit jax.jit's dispatch fast path;
# bounded LRU so churning groups can't grow it without bound.
import collections as _collections

_PROGRAM_CACHE = _collections.OrderedDict()
_PROGRAM_CACHE_CAP = 128


def _cached_program(key, build):
    prog = _PROGRAM_CACHE.get(key)
    if prog is None:
        while len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_CAP:
            _PROGRAM_CACHE.popitem(last=False)
        prog = _PROGRAM_CACHE[key] = build()
    else:
        _PROGRAM_CACHE.move_to_end(key)
    return prog


@dataclass
class Group:
    """A communicator: an ordered list of global ranks bound to a 1-D device
    mesh (axis name "g"). Parity: paddle.distributed.collective.Group."""

    ranks: list
    id: int = 0
    _mesh: Optional[Mesh] = field(default=None, repr=False)

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            devs = jax.devices()
            self._mesh = Mesh(
                np.array([devs[r % len(devs)] for r in self.ranks], dtype=object),
                ("g",),
            )
        return self._mesh


_default_group: Optional[Group] = None
_group_counter = [0]


def get_rank(group=None):
    import os

    if group is not None:
        return group.rank
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size(group=None):
    import os

    if group is not None:
        return group.nranks
    n = os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE"))
    if n is not None:
        return int(n)
    try:
        return jax.process_count() if jax.process_count() > 1 else 1
    except Exception:
        return 1


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        n = max(get_world_size(), 1)
        if n == 1:
            n = len(jax.devices())
        _default_group = Group(ranks=list(range(n)), id=0)
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    _group_counter[0] += 1
    if ranks is None:
        ranks = list(range(get_world_size()))
    g = Group(ranks=list(ranks), id=_group_counter[0])
    _groups_by_id[g.id] = g
    return g


_groups_by_id = {}


def get_group(gid=0):
    if gid == 0:
        return _get_default_group()
    return _groups_by_id.get(gid)


def destroy_process_group(group=None):
    global _default_group
    if group is None or group is _default_group:
        _default_group = None


def is_available():
    return True


# ---------------------------------------------------------------------------
# eager collective execution
# ---------------------------------------------------------------------------
def _collective_1d(group: Group, fn, x, extra_specs=()):
    """Run `fn(local_block)` as a shard_map over the group's 1-D mesh, with
    the input stacked along a leading group axis."""
    mesh = group.mesh
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(P("g"),) + tuple(extra_specs),
        out_specs=P("g"),
        check_vma=False,
    )(x)


def _stack_ranks(tensors):
    """Stack per-rank payloads into [nranks, ...] (single-controller path)."""
    return jnp.stack([t._data for t in tensors], axis=0)


def _this_rank_view(group, stacked, rank=None):
    r = rank if rank is not None else max(group.rank, 0)
    if _is_dist_multiprocess():
        # global indexing with a per-process-DIFFERENT index is not SPMD
        # (each process would contribute its row and GSPMD sums them);
        # this rank's row is exactly its addressable shard — read it directly
        for sh in stacked.addressable_shards:
            idx0 = sh.index[0] if sh.index else None
            start = (idx0.start or 0) if isinstance(idx0, slice) else 0
            if start == r:
                return jnp.asarray(np.asarray(sh.data)[0])
        # replicated case: any shard holds the full value
        return jnp.asarray(np.asarray(stacked.addressable_shards[0].data)[r])
    return stacked[r]


def _is_dist_multiprocess():
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               quantized=False):
    """In-place all_reduce of this rank's tensor across the group.

    ``quantized=True`` routes a SUM/AVG reduce through the EQuARX
    blockwise-int8 pipeline (collectives.quantized_all_reduce_rs_ag:
    int8 reduce-scatter with int32 accumulation + int8 all-gather, ~1
    byte/element on the wire per phase) — the group's 1-D mesh is a
    fully-manual region, where the gather/scatter lowering is valid."""
    group = group or _get_default_group()
    with _note_collective("all_reduce_q8" if quantized else "all_reduce",
                          group, tensor):
        if group.nranks <= 1:
            return tensor
        n = group.nranks
        if quantized and op not in (ReduceOp.SUM, ReduceOp.AVG):
            raise ValueError("quantized all_reduce supports SUM/AVG only")
        if _is_dist_multiprocess():
            # multi-controller: every process holds a same-shape local
            # tensor; reduce across the process dim via a global-array psum.
            stacked = _global_stack(tensor, group)
        else:
            stacked = jnp.broadcast_to(tensor._data,
                                       (n,) + tuple(tensor.shape))
        key = ("all_reduce", op, bool(quantized), tuple(group.ranks),
               tuple(stacked.shape), str(stacked.dtype))

        def build():
            if quantized:
                from ..collectives import quantized_all_reduce_rs_ag

                def red(x, ax):
                    return quantized_all_reduce_rs_ag(
                        x, ax, n, mean=op == ReduceOp.AVG)
            else:
                red = _resolve_reducer(op, n)
            return jax.jit(shard_map(
                lambda b: red(b, "g"), mesh=group.mesh,
                in_specs=(P("g"),), out_specs=P("g"), check_vma=False))

        out = _cached_program(key, build)(stacked)
        if quantized:
            from ..collectives import note_quantized_bytes

            note_quantized_bytes("all_reduce_q8", "g",
                                 int(tensor._data.nbytes))
        tensor._data = _this_rank_view(group, out)
    return tensor


def _global_stack(tensor, group):
    """Assemble [nranks, ...] global array from per-process local tensors."""
    sharding = NamedSharding(group.mesh, P("g"))
    local = np.asarray(tensor._data)[None]
    return jax.make_array_from_process_local_data(
        sharding, local, (group.nranks,) + local.shape[1:]
    )


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op=True, axis=0):
    group = group or _get_default_group()
    with _note_collective("all_gather", group, tensor):
        return _all_gather_impl(tensor_list, tensor, group)


def _all_gather_impl(tensor_list, tensor, group):
    if group.nranks <= 1:
        tensor_list.append(Tensor(tensor._data))
        return tensor_list
    if _is_dist_multiprocess():
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(np.asarray(tensor._data)))
        ranks = group.ranks  # select the group's members from the world gather
    else:
        out = np.broadcast_to(
            np.asarray(tensor._data), (group.nranks,) + tuple(tensor.shape)
        )
        ranks = range(group.nranks)
    for r in ranks:
        tensor_list.append(Tensor(jnp.asarray(out[r])))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    """Gather one picklable object per rank into `object_list` (len == nranks)."""
    import pickle

    group = group or _get_default_group()
    if not _is_dist_multiprocess():
        # single-controller SPMD: every "rank" holds an equal but independent
        # copy (matching the pickle round-trip aliasing of the multihost path)
        import copy

        object_list.extend(copy.deepcopy(obj) for _ in range(group.nranks))
        return object_list
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # pad to a common length so process_allgather sees uniform shapes
    size = np.asarray([payload.size])
    sizes = np.asarray(multihost_utils.process_allgather(size)).reshape(-1)
    buf = np.zeros(int(sizes.max()), np.uint8)
    buf[: payload.size] = payload
    gathered = np.asarray(multihost_utils.process_allgather(buf[None]))
    gathered = gathered.reshape(-1, buf.size)
    for r in group.ranks:
        object_list.append(pickle.loads(bytes(gathered[r][: int(sizes[r])])))
    return object_list


def reduce(tensor: Tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    group = group or _get_default_group()
    all_reduce(tensor, op=op, group=group, sync_op=sync_op)
    return tensor


def broadcast(tensor: Tensor, src, group=None, sync_op=True):
    group = group or _get_default_group()
    with _note_collective("broadcast", group, tensor):
        if group.nranks <= 1:
            return tensor
        if _is_dist_multiprocess():
            from jax.experimental import multihost_utils

            root = group.get_group_rank(src)
            val = multihost_utils.broadcast_one_to_all(
                np.asarray(tensor._data), is_source=(group.rank == root)
            )
            tensor._data = jnp.asarray(val)
    return tensor


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    """Each rank contributes `tensor_list` (n tensors); rank r receives the
    cross-rank reduction of everyone's slot r. Single-controller simulation
    mirrors all_reduce: every "rank" holds the same inputs, so slot r sums
    to n * tensor_list[r]."""
    group = group or _get_default_group()
    with _note_collective("reduce_scatter", group, *tensor_list):
        if group.nranks <= 1:
            tensor._data = tensor_list[0]._data
            return tensor
        cat = jnp.stack([t._data for t in tensor_list], 0)  # rank: [n, ...]
        if _is_dist_multiprocess():
            g = _global_stack(Tensor(cat), group)  # [nprocs, n, ...]
        else:
            g = jnp.broadcast_to(cat, (group.nranks,) + tuple(cat.shape))

        key = ("reduce_scatter", op, tuple(group.ranks),
               tuple(g.shape), str(g.dtype))

        def build():
            reducer = _resolve_reducer(op, group.nranks)

            def _rs(block):  # [1, n, ...] -> this rank's reduced shard
                red = reducer(block[0], "g")  # [n, ...]
                idx = jax.lax.axis_index("g")
                return jax.lax.dynamic_slice_in_dim(red, idx, 1, 0)

            return jax.jit(shard_map(
                _rs, mesh=group.mesh, in_specs=(P("g"),),
                out_specs=P("g"), check_vma=False))

        out = _cached_program(key, build)(g)  # [n, ...], row r = rank r
        tensor._data = _this_rank_view(group, out)
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """SPMD all_to_all. Single-controller simulation: all ranks hold the same
    inputs, so rank r's output list is [in[r]] * n — consistent with the
    degenerate all_reduce/reduce_scatter semantics above."""
    group = group or _get_default_group()
    with _note_collective("all_to_all", group, *in_tensor_list):
        return _all_to_all_impl(out_tensor_list, in_tensor_list, group)


def _all_to_all_impl(out_tensor_list, in_tensor_list, group):
    n = group.nranks
    if n <= 1 or not _is_dist_multiprocess():
        r = max(group.rank, 0)
        src_t = in_tensor_list[min(r, len(in_tensor_list) - 1)]
        out_tensor_list.extend(Tensor(src_t._data) for _ in range(max(n, 1)))
        return out_tensor_list
    cat = jnp.stack([t._data for t in in_tensor_list], 0)
    g = _global_stack(Tensor(cat), group)  # [nprocs, n, ...]

    def _a2a(block):  # local [1, n, ...] -> local [n, 1, ...]: dim0 = source
        return jax.lax.all_to_all(block, "g", split_axis=1, concat_axis=0)

    mesh = group.mesh
    out = shard_map(
        _a2a, mesh=mesh, in_specs=(P("g"),), out_specs=P(None, "g"), check_vma=False
    )(g)  # global [n, n, ...]; column r = rank r's received list
    # this rank's column IS its addressable shard (global indexing with a
    # per-process index is not SPMD — see _this_rank_view)
    row = np.asarray(out.addressable_shards[0].data)[:, 0]
    for i in range(n):
        out_tensor_list.append(Tensor(jnp.asarray(row[i])))
    return out_tensor_list


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    group = group or _get_default_group()
    if tensor_list:
        tensor._data = tensor_list[max(group.rank, 0)]._data
    return tensor


def gather(tensor: Tensor, gather_list=None, dst=0, group=None, sync_op=True):
    group = group or _get_default_group()
    if gather_list is not None:
        return all_gather(gather_list, tensor, group=group)
    return tensor


# Eager p2p. Three tiers (parity: ProcessGroupNCCL point-to-point on the
# comm stream, fluid/distributed/collective/process_group_nccl.cc):
#   1. jax multi-controller live  -> compiled lax.ppermute over the
#      2-device {src, dst} mesh — the transfer rides ICI/DCN, only the two
#      owning processes enter the program.
#   2. single controller          -> in-process mailbox + the same compiled
#      ppermute moving the payload onto the dst rank's device.
#   3. PADDLE_MASTER w/o jax.distributed -> TCPStore mailbox (control-plane
#      fallback; correctness only).
_p2p_store = [None]
_p2p_seq = {}
_p2p_inproc = {}


def _p2p_pair_transfer(data, src, dst, dtype=None):
    """Compiled point-to-point: ppermute over the 2-device {src, dst} mesh.

    ``data`` is this process's contribution for the mesh rows it owns (the
    payload on the src process, a same-shape placeholder on the dst).
    Returns the transferred row (meaningful on the dst process)."""
    devs = jax.devices()

    def _dev_of(rank):
        # multi-host with several chips per process: rank r's endpoint is
        # a device OWNED by r's process (ranks map 1:1 to processes in
        # that deployment); single-controller keeps the ambient
        # rank-per-device convention
        if _is_dist_multiprocess() and get_world_size() == jax.process_count():
            mine = [d for d in devs if d.process_index == rank]
            if mine:
                return mine[0]
        return devs[rank % len(devs)]

    sd, dd = _dev_of(src), _dev_of(dst)
    arr = jnp.asarray(data)
    if dtype is not None:
        arr = arr.astype(dtype)
    if sd == dd:
        return arr
    mesh = Mesh(np.array([sd, dd], dtype=object), ("p",))
    sharding = NamedSharding(mesh, P("p"))
    shape = (2,) + tuple(arr.shape)
    if _is_dist_multiprocess():
        me = jax.process_index()
        rows = []
        if sd.process_index == me:
            rows.append(np.asarray(arr))
        if dd.process_index == me:
            rows.append(np.zeros_like(np.asarray(arr)))
        if not rows:
            raise RuntimeError(
                f"p2p transfer {src}->{dst}: this process owns neither "
                "endpoint device")
        local = np.stack(rows, axis=0)
        stacked = jax.make_array_from_process_local_data(
            sharding, local, shape)
    else:
        stacked = jax.device_put(
            jnp.stack([arr, jnp.zeros_like(arr)], axis=0), sharding)
    out = shard_map(
        lambda b: jax.lax.ppermute(b, "p", perm=[(0, 1)]),
        mesh=mesh, in_specs=(P("p"),), out_specs=P("p"), check_vma=False,
    )(stacked)
    if _is_dist_multiprocess():
        for sh in out.addressable_shards:
            if sh.device == dd:
                return jnp.asarray(np.asarray(sh.data)[0])
        return arr  # src side: nothing to read back
    return jax.device_put(out[1], dd)  # land on the dst rank's device


def _get_p2p_store():
    if _p2p_store[0] is None:
        import os

        master = os.environ.get("PADDLE_MASTER")
        if master is None:
            raise NotImplementedError(
                "eager send/recv needs a multi-controller run (PADDLE_MASTER "
                "set by the launcher); in-program transfers compile to "
                "lax.ppermute (paddle_tpu.distributed.pipeline)")
        from ..store import TCPStore

        host, port = master.rsplit(":", 1)
        # the master port itself hosts the jax coordinator; p2p rides +1
        _p2p_store[0] = TCPStore(host=host, port=int(port) + 1,
                                 is_master=get_rank() == 0,
                                 world_size=get_world_size())
    return _p2p_store[0]


def send(tensor, dst=0, group=None, sync_op=True):
    """Eager point-to-point send.

    Multi-controller tier: dtypes MUST match on both ends — each side
    compiles its half of one shared XLA program, so a mismatch means
    mismatched programs (the same contract as NCCL send/recv in the
    reference, process_group_nccl.cc). The single-controller and store
    tiers cast to the recv buffer's dtype as a convenience."""
    _note_collective("send", group or _get_default_group(), tensor)
    src = get_rank()
    # role-scoped sequence counters: in the single-controller simulation
    # the sending and receiving "ranks" share this process, so one shared
    # counter would double-count
    seq = _p2p_seq.setdefault(("send", src, dst), [0])
    n = seq[0]
    seq[0] += 1
    if _is_dist_multiprocess():
        # both endpoints enter the same 2-device compiled transfer; the
        # matching recv() on the dst process supplies the placeholder row
        _p2p_pair_transfer(tensor._data, src, dst)
        return tensor
    import os

    if os.environ.get("PADDLE_MASTER") and get_world_size() > 1:
        import pickle

        store = _get_p2p_store()
        store.set(f"p2p/{src}/{dst}/{n}",
                  pickle.dumps(np.asarray(tensor._data), protocol=4))
        return tensor
    # single controller: mailbox of device arrays, drained by recv()
    _p2p_inproc[(src, dst, n)] = tensor._data
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    _note_collective("recv", group or _get_default_group(), tensor)
    dst = get_rank()
    seq = _p2p_seq.setdefault(("recv", src, dst), [0])
    n = seq[0]
    seq[0] += 1
    dtype = tensor._data.dtype
    if _is_dist_multiprocess():
        out = _p2p_pair_transfer(jnp.zeros_like(tensor._data), src, dst,
                                 dtype=dtype)
        tensor._data = out
        return tensor
    import os

    if os.environ.get("PADDLE_MASTER") and get_world_size() > 1:
        import pickle

        store = _get_p2p_store()
        key = f"p2p/{src}/{dst}/{n}"
        store.wait(key)
        val = np.asarray(pickle.loads(store.get(key)))
        store.delete_key(key)  # the store is a mailbox, not an archive
        tensor._data = jnp.asarray(val).astype(dtype)
        return tensor
    data = _p2p_inproc.pop((src, dst, n), None)
    if data is None:
        raise RuntimeError(
            f"recv(src={src}) found no matching send (dst={dst}, seq={n}); "
            "single-controller eager p2p requires send before recv")
    tensor._data = _p2p_pair_transfer(data, src, dst, dtype=dtype)
    return tensor


def barrier(group=None):
    if _is_dist_multiprocess():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu_barrier")
    return None


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._data.block_until_ready()


# stream namespace: the real submodule (communication/stream.py) is the
# single surface — imported at the bottom so `communication.stream`
# always resolves to it regardless of import order
from . import stream  # noqa: F401,E402
