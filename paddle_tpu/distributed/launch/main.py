"""``python -m paddle_tpu.distributed.launch`` — multi-host job launcher.

Capability parity: `python/paddle/distributed/launch/main.py:23` +
`controllers/collective.py` (pod/process model, env contract, restart).

TPU-native process model: ONE controller process per HOST drives all local
chips (multi-controller jax), so ``--nproc_per_node`` defaults to 1 on TPU
— unlike the reference's process-per-GPU. Values > 1 are used by the
CPU fake-backend test path (each process becomes one "rank").

Env contract written for each process (consumed by init_parallel_env):
  PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_MASTER,
  PADDLE_LOCAL_RANK, PADDLE_NNODES, PADDLE_JOB_ID

Rendezvous: ``--master host:port`` backed by the native TCPStore
(core/native/store.cc); with ``--rank -1`` node ranks are auto-assigned
by an atomic ADD on the store. ``--max_restart`` relaunches failed
processes (elastic restart-from-checkpoint model, SURVEY §5 failure
detection).
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def _parse():
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="TPU-native distributed launcher",
    )
    p.add_argument("--master", default=None,
                   help="rendezvous server host:port (TCPStore)")
    p.add_argument("--rank", type=int, default=-1,
                   help="node rank; -1 = auto-assign via master")
    p.add_argument("--nnodes", default="1",
                   help="number of nodes (elastic range 'lo:hi' takes lo)")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", default=None,
                   help="accepted for API parity; the TPU runtime binds all "
                        "local chips to the one controller process")
    p.add_argument("--max_restart", type=int, default=0)
    p.add_argument("--elastic_level", type=int, default=-1)
    p.add_argument("--elastic_timeout", type=int, default=30)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def _nnodes(spec: str) -> int:
    return int(str(spec).split(":")[0])


def _rendezvous(master: str, rank: int, nnodes: int, job_id: str):
    """Return (node_rank, store_or_none). Starts the store on the master
    node (the one whose --rank is 0 or that can bind the port)."""
    from ..store import TCPStore

    host, port = master.split(":")
    port = int(port)
    store = None
    if rank == 0 or rank == -1:
        try:
            store = TCPStore(host="127.0.0.1", port=port, is_master=True,
                             world_size=nnodes)
        except Exception:
            store = None  # another node owns the master port
    if store is None:
        store = TCPStore(host=host, port=port, is_master=False,
                         world_size=nnodes)
    if rank == -1:
        rank = store.add(f"{job_id}/node_count", 1) - 1
    store.set(f"{job_id}/node/{rank}", str(os.getpid()))
    return rank, store


def _spawn_ranks(args, node_rank, nproc, world, script_args, generation=0):
    """Spawn `nproc` local rank processes; returns (procs, logfiles)."""
    procs, logs = [], []
    for i in range(nproc):
        rank = node_rank * nproc + i
        env = dict(os.environ)
        env.update(
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM=str(world),
            PADDLE_LOCAL_RANK=str(i),
            PADDLE_NNODES=str(max(world // max(nproc, 1), 1)),
            PADDLE_JOB_ID=args.job_id,
            PADDLE_ELASTIC_GENERATION=str(generation),
            FLAGS_selected_tpus=str(i),
        )
        if args.master:
            env["PADDLE_MASTER"] = args.master
        log_path = os.path.join(args.log_dir, f"{args.job_id}.{rank}.log")
        lf = open(log_path, "ab")
        logs.append(lf)
        procs.append(subprocess.Popen(
            [sys.executable, args.training_script] + script_args,
            env=env, stdout=lf, stderr=subprocess.STDOUT,
        ))
    return procs, logs


def _launch_elastic(args, node_rank, nproc, min_world, script_args) -> None:
    """Elastic (level 2) process supervision: scale-in AND scale-out
    re-rendezvous.

    Capability parity: fleet/elastic/manager.py:462 `_match` + pod
    relaunch — on member death the job does NOT abort: the survivors are
    re-launched as a new *generation* with the shrunken world size (as
    long as it stays >= the `--nnodes lo` bound), and training resumes
    from checkpoint. Scale-out: a (re)joining member calls
    ElasticManager.request_join() against the job store (`--master`);
    the supervisor honors pending requests up to the original world by
    relaunching the next generation larger. Generation numbers reach
    workers via PADDLE_ELASTIC_GENERATION.
    """
    # Dedicated supervisor store on an EPHEMERAL port — never the --master
    # port, which rank 0 must bind for jax.distributed / rendezvous. The
    # endpoint reaches workers via PADDLE_ELASTIC_ENDPOINT; external
    # rejoiners get it out-of-band (it is printed on startup).
    from ..fleet.elastic import _store_int
    from ..store import TCPStore

    store = TCPStore(host="127.0.0.1", port=0, is_master=True, world_size=1)
    endpoint = f"127.0.0.1:{store.port}"
    os.environ["PADDLE_ELASTIC_ENDPOINT"] = endpoint
    sys.stderr.write(f"elastic: supervisor endpoint {endpoint}\n")

    def _pending_joins():
        raw = store.get("elastic/join_requests")
        return _store_int(raw) if raw else 0

    def _consume_joins(k):
        store.add("elastic/join_requests", -int(k))

    world = nproc
    generation = 0
    relaunches = 0
    while True:
        procs, logs = _spawn_ranks(args, node_rank, world, world,
                                   script_args, generation)
        # supervise: a dead member must trigger re-rendezvous IMMEDIATELY —
        # survivors may be blocked in a collective waiting for it, so
        # waiting for all ranks to exit would deadlock the job
        codes = [None] * world
        scale_out = 0
        last_join_check = 0.0
        while any(c is None for c in codes):
            time.sleep(0.2)
            codes = [p.poll() for p in procs]
            if any(c is not None and c != 0 for c in codes):
                for p, c in zip(procs, codes):
                    if c is None:
                        p.terminate()
                for p in procs:
                    p.wait()
                codes = [p.returncode for p in procs]
                break
            now = time.time()
            if now - last_join_check > 0.3:
                last_join_check = now
                joins = _pending_joins()
                if joins > 0:
                    grow = min(joins, nproc - world)
                    # consume EVERY pending request: capacity-exceeding
                    # requests are discarded, not banked — a stale request
                    # must never trigger a surprise re-rendezvous later
                    _consume_joins(joins)
                    if grow > 0:
                        for p in procs:
                            p.terminate()
                        for p in procs:
                            p.wait()
                        codes = [p.returncode for p in procs]
                        scale_out = grow
                        break
        for lf in logs:
            lf.close()
        if scale_out:
            relaunches += 1  # scale-out counts against max_restart too:
            if relaunches > args.max_restart:  # bounds join/term loops
                sys.stderr.write(
                    f"elastic: relaunch budget exhausted "
                    f"({relaunches}/{args.max_restart})\n")
                sys.exit(1)
            generation += 1
            world += scale_out
            sys.stderr.write(
                f"elastic: {scale_out} member(s) joined; re-rendezvous "
                f"generation {generation} with world {world}\n")
            time.sleep(0.3)
            continue
        if all(c == 0 for c in codes):
            store.close()
            return
        # terminated survivors (negative returncode from our SIGTERM) are
        # still members; only self-failed ranks count as dead
        n_dead = sum(1 for c in codes if c is not None and c > 0)
        n_dead = max(n_dead, 1)
        new_world = world - n_dead
        relaunches += 1
        if new_world < min_world or relaunches > args.max_restart:
            sys.stderr.write(
                f"elastic: cannot continue (world {world} -> {new_world}, "
                f"min {min_world}, relaunch {relaunches}/{args.max_restart})\n")
            sys.exit(next((c for c in codes if c and c > 0), 1))
        generation += 1
        sys.stderr.write(
            f"elastic: {n_dead} member(s) lost; re-rendezvous generation "
            f"{generation} with world {new_world}\n")
        world = new_world
        time.sleep(0.5)


def launch() -> None:
    args = _parse()
    nnodes = _nnodes(args.nnodes)
    nproc = args.nproc_per_node or 1
    node_rank = max(args.rank, 0)
    store = None
    if args.master and nnodes > 1:
        node_rank, store = _rendezvous(args.master, args.rank, nnodes,
                                       args.job_id)

    world = nnodes * nproc
    os.makedirs(args.log_dir, exist_ok=True)
    script_args = [a for a in args.training_script_args if a != "--"]

    if args.elastic_level >= 2 and nnodes == 1:
        _launch_elastic(args, node_rank, nproc, nnodes, script_args)
        if store is not None:
            store.close()
        return
    if args.elastic_level >= 2 and nnodes > 1:
        # Per-rank elastic supervision is single-node only today; multi-node
        # jobs degrade to the whole-job restart loop below. Say so loudly
        # instead of silently downgrading the documented behavior.
        sys.stderr.write(
            "paddle_tpu.launch: --elastic_level >= 2 with nnodes > 1 is not "
            "supported; falling back to whole-job restart (max_restart="
            f"{args.max_restart}). Scale-in/out supervision runs only with "
            "nnodes == 1.\n")

    for attempt in range(args.max_restart + 1):
        procs, logs = _spawn_ranks(args, node_rank, nproc, world, script_args)
        codes = [p.wait() for p in procs]
        for lf in logs:
            lf.close()
        if all(c == 0 for c in codes):
            break
        if attempt == args.max_restart:
            for rank, c in enumerate(codes):
                if c != 0:
                    log_path = os.path.join(
                        args.log_dir, f"{args.job_id}.{node_rank * nproc + rank}.log")
                    sys.stderr.write(
                        f"rank {rank} exited {c}; last log lines "
                        f"({log_path}):\n")
                    try:
                        with open(log_path, "rb") as f:
                            sys.stderr.write(
                                f.read()[-2000:].decode(errors="replace"))
                    except OSError:
                        pass
            sys.exit(next((c for c in codes if c and c > 0), 1))
        time.sleep(1.0)

    if store is not None:
        store.close()
