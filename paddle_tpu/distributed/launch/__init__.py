"""Launcher package (parity: python/paddle/distributed/launch)."""
from .main import launch  # noqa: F401
