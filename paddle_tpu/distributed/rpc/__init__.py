"""paddle.distributed.rpc — process RPC (parity: distributed/rpc/rpc.py:85
init_rpc / rpc_sync / rpc_async / shutdown over the C++ brpc agent).

TPU-native transport: the native TCPStore carries pickled call requests
and results (control-plane RPC only; tensor traffic rides XLA
collectives). Each worker runs a poller thread that executes requests
addressed to it. Single-process mode executes calls inline.
"""
from __future__ import annotations

import pickle
import threading
import time
import uuid

from ..store import TCPStore


class WorkerInfo:
    def __init__(self, name, rank, ip=None, port=None):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return f"WorkerInfo(name={self.name}, rank={self.rank})"


_state = {
    "store": None, "name": None, "rank": 0, "world": 1,
    "workers": {}, "poller": None, "stop": False,
}


def init_rpc(name, rank=0, world_size=1, master_endpoint=None):
    if master_endpoint:
        host, port = master_endpoint.split(":")
        store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                         world_size=world_size)
    else:
        store = TCPStore(is_master=True, world_size=1)
    _state.update(store=store, name=name, rank=rank, world=world_size,
                  stop=False)
    store.set(f"rpc/worker/{rank}", name)
    _state["workers"][name] = WorkerInfo(name, rank)

    def poll():
        seq = 0
        while not _state["stop"]:
            req = store.get(f"rpc/call/{name}/{seq}")
            if req is None:
                time.sleep(0.01)
                continue
            call_id, fn, args, kwargs = pickle.loads(req)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # deliver the exception to the caller
                result = (False, e)
            store.set(f"rpc/result/{call_id}", pickle.dumps(result))
            seq += 1

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    _state["poller"] = t


def get_worker_info(name=None):
    if name is None:
        name = _state["name"]
    if name in _state["workers"]:
        return _state["workers"][name]
    # discover via store
    store = _state["store"]
    for r in range(_state["world"]):
        n = store.get(f"rpc/worker/{r}")
        if n is not None and n.decode() == name:
            info = WorkerInfo(name, r)
            _state["workers"][name] = info
            return info
    raise ValueError(f"unknown rpc worker {name!r}")


def get_all_worker_infos():
    store = _state["store"]
    infos = []
    for r in range(_state["world"]):
        n = store.get(f"rpc/worker/{r}")
        if n is not None:
            infos.append(WorkerInfo(n.decode(), r))
    return infos


class _Future:
    def __init__(self, call_id, inline_result=None, done=False):
        self._call_id = call_id
        self._result = inline_result
        self._done = done

    def wait(self, timeout=60.0):
        if self._done:
            ok, val = self._result
            if not ok:
                raise val
            return val
        store = _state["store"]
        deadline = time.time() + timeout
        while time.time() < deadline:
            raw = store.get(f"rpc/result/{self._call_id}")
            if raw is not None:
                ok, val = pickle.loads(raw)
                self._done = True
                self._result = (ok, val)
                if not ok:
                    raise val
                return val
            time.sleep(0.01)
        raise TimeoutError(f"rpc call {self._call_id} timed out")


_seq_counters = {}


def rpc_async(to, fn, args=None, kwargs=None, timeout=60.0):
    args = args or ()
    kwargs = kwargs or {}
    if to == _state["name"]:
        try:
            return _Future(None, (True, fn(*args, **kwargs)), done=True)
        except Exception as e:
            return _Future(None, (False, e), done=True)
    call_id = uuid.uuid4().hex
    seq = _seq_counters.get(to, 0)
    _seq_counters[to] = seq + 1
    _state["store"].set(
        f"rpc/call/{to}/{seq}",
        pickle.dumps((call_id, fn, args, kwargs)),
    )
    return _Future(call_id)


def rpc_sync(to, fn, args=None, kwargs=None, timeout=60.0):
    return rpc_async(to, fn, args, kwargs, timeout).wait(timeout)


def shutdown(graceful=True):
    _state["stop"] = True
    if _state["poller"] is not None:
        _state["poller"].join(timeout=2)
    if _state["store"] is not None:
        _state["store"].close()
    _state.update(store=None, poller=None)


def get_current_worker_info():
    """parity: rpc.get_current_worker_info — this process's WorkerInfo."""
    name = _state["name"]
    if name is None:
        raise RuntimeError("rpc is not initialized; call init_rpc first")
    return _state["workers"][name]
