"""Hybrid-parallel topology (parity: fleet/base/topology.py).

The reference builds per-strategy communicator groups (dp/mp/pp/sep/sharding
+ fused axes + p2p rings) out of process ranks (`topology.py:189,343,412`).
TPU-native redesign: the topology IS a ProcessMesh over the device grid —
one `jax.sharding.Mesh` with axes ("pp", "dp", "sharding", "sep", "mp").
Groups become mesh axes; collectives become XLA ops over those axes; there
are no per-group communicators to create.

Axis order is chosen TPU-first: "mp" is the innermost (fastest-varying)
axis so tensor-parallel collectives ride adjacent-chip ICI links, then
sep/sharding/dp, with "pp" outermost (its ppermute traffic is lightest).
The reference's rank-assignment order (pp->mp->sep->sharding->dp,
`topology.py:298`) is a CUDA-cluster artifact we deliberately do not copy.
"""
from __future__ import annotations

import os

import numpy as np

from ..auto_parallel import ProcessMesh

_AXES = ("pp", "dp", "sharding", "sep", "mp")


class CommunicateTopology:
    """Named hybrid axes -> coordinates (parity: topology.py CommunicateTopology)."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._names = list(hybrid_group_names or _AXES)
        self._dims = list(dims or [1] * len(self._names))

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = [kwargs[n] for n in self._names]
        return int(np.ravel_multi_index(coord, self._dims))

    def get_coord(self, rank):
        return dict(zip(self._names, np.unravel_index(rank, self._dims)))


class _AxisGroup:
    """A group view over one mesh axis at this rank's coordinates."""

    def __init__(self, hcg, axis):
        self._hcg = hcg
        self._axis = axis

    @property
    def nranks(self):
        return self._hcg.topo.get_dim(self._axis)

    world_size = nranks

    @property
    def rank(self):
        return self._hcg.coord[self._axis]

    @property
    def ranks(self):
        # global ranks along this axis, holding other coords fixed
        dims = self._hcg.topo._dims
        names = self._hcg.topo._names
        coord = dict(self._hcg.coord)
        out = []
        for i in range(self._hcg.topo.get_dim(self._axis)):
            coord[self._axis] = i
            out.append(self._hcg.topo.get_rank(**coord))
        return out

    @property
    def axis_name(self):
        return self._axis

    @property
    def process_group(self):
        return self


class HybridCommunicateGroup:
    """Parity: topology.py:189 HybridCommunicateGroup — mesh-backed."""

    def __init__(self, topology: CommunicateTopology = None, mesh: ProcessMesh = None):
        self.topo = topology
        self.mesh = mesh
        self.global_rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.coord = topology.get_coord(self.global_rank)
        self._groups = {a: _AxisGroup(self, a) for a in topology.get_hybrid_group_names()}

    @property
    def nranks(self):
        return self.topo.world_size()

    # ---- per-strategy accessors (reference API names) -------------------
    def get_data_parallel_world_size(self):
        return self.topo.get_dim("dp")

    def get_data_parallel_rank(self):
        return self.coord["dp"]

    def get_data_parallel_group(self):
        return self._groups["dp"]

    def get_data_parallel_group_src_rank(self):
        return self._groups["dp"].ranks[0]

    def get_model_parallel_world_size(self):
        return self.topo.get_dim("mp")

    def get_model_parallel_rank(self):
        return self.coord["mp"]

    def get_model_parallel_group(self):
        return self._groups["mp"]

    def get_model_parallel_group_src_rank(self):
        return self._groups["mp"].ranks[0]

    def get_pipe_parallel_world_size(self):
        return self.topo.get_dim("pp")

    def get_stage_id(self):
        return self.coord["pp"]

    def get_pipe_parallel_group(self):
        return self._groups["pp"]

    def get_sharding_parallel_world_size(self):
        return self.topo.get_dim("sharding")

    def get_sharding_parallel_rank(self):
        return self.coord["sharding"]

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_world_size(self):
        return self.topo.get_dim("sep")

    def get_sep_parallel_rank(self):
        return self.coord["sep"]

    def get_sep_parallel_group(self):
        return self._groups["sep"]

    def is_first_stage(self):
        return self.coord["pp"] == 0

    def is_last_stage(self):
        return self.coord["pp"] == self.topo.get_dim("pp") - 1

    def get_p2p_groups(self):
        return None  # p2p is ppermute inside compiled pipeline programs

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = dict(self.coord)
        coord["pp"] = stage_id
        coord.update(kwargs)
        return self.topo.get_rank(**coord)


def build_hybrid_mesh(dp=1, mp=1, pp=1, sharding=1, sep=1):
    """Build the fleet ProcessMesh over however many devices the degrees need.

    Returns (topology, hcg, mesh). Degrees must multiply to the available
    device count (or fewer — remaining devices stay idle, matching the
    reference's requirement that nranks == product of degrees).
    """
    import jax

    dims = [pp, dp, sharding, sep, mp]
    n = int(np.prod(dims))
    avail = len(jax.devices())
    if n > avail:
        raise ValueError(
            f"hybrid degrees {dict(zip(_AXES, dims))} need {n} devices, "
            f"have {avail}"
        )
    topo = CommunicateTopology(list(_AXES), dims)
    mesh = ProcessMesh(
        np.arange(n).reshape(dims), dim_names=list(_AXES)
    )
    hcg = HybridCommunicateGroup(topo, mesh)
    return topo, hcg, mesh
