"""fleet.elastic — elastic training manager (parity: fleet/elastic/
manager.py:125 ElasticManager over etcd leases).

TPU-native: heartbeats and membership live in the native TCPStore (no
etcd in the image); fault tolerance is restart-from-checkpoint, driven by
the launcher's --max_restart (launch/main.py), same recovery model as the
reference (SURVEY §5 failure detection).
"""
from __future__ import annotations

import os
import threading
import time


class ElasticLevel:
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, args=None, etcd_client=None, store=None):
        self.args = args
        self._store = store
        self._stop = False
        self._hb = None
        self.host = os.environ.get("POD_IP", "127.0.0.1")
        self.np = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.enabled = store is not None or (
            args is not None and getattr(args, "elastic_level", -1) > 0)
        if self.enabled and self._store is None:
            from ...store import TCPStore

            master = os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
            host, port = master.split(":")
            rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            self._store = TCPStore(host=host, port=int(port),
                                   is_master=(rank == 0), world_size=self.np)

    def start_heartbeat(self, interval=2.0):
        if not self.enabled:
            return

        def beat():
            rank = os.environ.get("PADDLE_TRAINER_ID", "0")
            while not self._stop:
                self._store.set(f"elastic/beat/{rank}",
                                str(time.time()).encode())
                time.sleep(interval)

        self._hb = threading.Thread(target=beat, daemon=True)
        self._hb.start()

    def alive_ranks(self, timeout=10.0):
        if not self.enabled:
            return list(range(self.np))
        now = time.time()
        alive = []
        for r in range(self.np):
            raw = self._store.get(f"elastic/beat/{r}")
            if raw is not None and now - float(raw) < timeout:
                alive.append(r)
        return alive

    def should_restart(self):
        return self.enabled and len(self.alive_ranks()) < self.np

    def exit(self, completed=True):
        self._stop = True
        if self._hb is not None:
            self._hb.join(timeout=3)
        if self._store is not None:
            self._store.close()
            self._store = None
