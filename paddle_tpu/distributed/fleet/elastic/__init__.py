"""fleet.elastic — elastic training manager.

Parity: `fleet/elastic/manager.py:125-520` (ElasticManager over etcd
leases: node registration with TTL heartbeats, watch-driven membership
change detection, scale in/out between `--nnodes lo:hi`, restart with a
new world size, resume from checkpoint).

TPU-native redesign: membership lives in the native TCPStore
(core/native/store.cc) instead of etcd — heartbeat keys with timestamps
stand in for leases, and a monotonically increasing **generation
number** stands in for the etcd watch: any member that observes a
generation bump stops, re-registers under the new generation, and gets
a dense new rank. The launcher (`launch/main.py --elastic_level 2`)
drives the process side: on worker death it re-launches the survivors
with the shrunken world size (scale-in) as long as it stays >= the
`--nnodes lo` bound; recovery of state is checkpoint-resume, same model
as the reference (SURVEY §5 failure detection).

Why restart-based (investigated r3): IN-PROCESS mesh rebuild — survivors
re-running `jax.distributed.initialize` with the new world — is blocked
by jax itself: `initialize()` refuses to run once the XLA backend has
been touched (distributed.py guard), and `jax.clear_backends()` does not
reset that guard. Until jax supports re-initialisation, process restart
+ checkpoint resume is the only supported recovery, which is also the
reference's model (`fleet/elastic/manager.py` restarts training).
"""
from __future__ import annotations

import os
import threading
import time


def _store_int(raw: bytes) -> int:
    """Decode a store counter: ascii int (set) or the native store's
    atomic-ADD 8-byte little-endian representation."""
    try:
        return int(raw)
    except ValueError:
        return int.from_bytes(raw, "little")


# ---------------------------------------------------------------------------
# Checkpoint auto-resume: the recovery half of restart-based elasticity.
# A relaunched member (new generation, possibly new rank/world) calls
# `auto_resume` with the job's checkpoint root; only COMMITTED steps are
# considered (CheckpointManager's COMMIT/checksum contract), so a member
# killed mid-save resumes from the previous good step instead of loading
# the partial one — the failure mode this subsystem exists to survive.
# ---------------------------------------------------------------------------
def latest_checkpoint_step(ckpt_root):
    """Newest committed step under `ckpt_root` a resume may land on, or
    None (fresh start). Steps the resilience guard marked BAD
    (docs/RESILIENCE.md) are skipped — resuming into a state the guard
    rewound away from would replay the poisoning."""
    from ...checkpoint.manager import CheckpointManager

    return CheckpointManager(ckpt_root).last_good_step()


def auto_resume(ckpt_root, model=None, optimizer=None, strict=True):
    """Resolve ``--resume auto`` after an elastic restart: restore the
    newest committed-and-valid step into `model` (+ `optimizer`) and
    return it, or None when no committed checkpoint exists. Validation
    failures fall back to older committed steps and guard-marked BAD
    steps are skipped (restore() semantics); with `model=None` only the
    resume step is resolved, through the SAME good-and-valid walk a
    restoring worker performs — supervisor and worker agree on the
    resume point even when the newest good step is corrupt."""
    from ...checkpoint.manager import CheckpointManager, NoCheckpointError

    mgr = CheckpointManager(ckpt_root)
    try:
        if model is None:
            for s in reversed(mgr.good_steps()):
                if not mgr.validate_step(s):
                    return s
            return None
        return mgr.restore_training_state(model, optimizer=optimizer,
                                          strict=strict)
    except NoCheckpointError:
        return None


class ElasticLevel:
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Store-backed membership with generation numbers.

    Keys (all under ``elastic/``):
      generation              int — bumped on every membership change
      gen/{g}/members/{id}    heartbeat timestamp of member `id` in gen g
      gen/{g}/rank            atomic counter for dense re-rank assignment
      gen/{g}/world           world size frozen for generation g
    """

    def __init__(self, args=None, etcd_client=None, store=None,
                 heartbeat_interval=1.0, heartbeat_timeout=6.0):
        self.args = args
        self._store = store
        self._stop = False
        self._hb = None
        self.host = os.environ.get("POD_IP", "127.0.0.1")
        self.np = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.member_id = os.environ.get("PADDLE_TRAINER_ID", "0")
        self._announced_gens = set()
        self.heartbeat_interval = heartbeat_interval
        self.heartbeat_timeout = heartbeat_timeout
        self.enabled = store is not None or (
            args is not None and getattr(args, "elastic_level", -1) > 0)
        if self.enabled and self._store is None:
            from ...store import TCPStore

            master = os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
            host, port = master.split(":")
            rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
            self._store = TCPStore(host=host, port=int(port),
                                   is_master=(rank == 0), world_size=self.np)

    # -- generation ---------------------------------------------------------
    def generation(self) -> int:
        if not self.enabled:
            return 0
        raw = self._store.get("elastic/generation")
        return _store_int(raw) if raw else 0

    def bump_generation(self) -> int:
        """Coordinator: announce a membership change. Returns the new gen."""
        return self._store.add("elastic/generation", 1)

    # -- membership ---------------------------------------------------------
    def register(self, member_id=None, generation=None):
        """Join the current generation's membership and start heartbeats."""
        if not self.enabled:
            return
        if member_id is not None:
            self.member_id = str(member_id)
        gen = self.generation() if generation is None else generation
        self.announce(gen)
        self._beat(gen)
        if self._hb is None:
            self._hb = threading.Thread(target=self._beat_loop, daemon=True)
            self._hb.start()

    def _beat(self, gen):
        self._store.set(
            f"elastic/gen/{gen}/members/{self.member_id}",
            str(time.time()).encode())

    def _beat_loop(self):
        while not self._stop:
            try:
                self._beat(self.generation())
            except Exception:
                return  # store gone: job is tearing down
            time.sleep(self.heartbeat_interval)

    def alive_members(self, gen=None, timeout=None):
        """Member ids with a fresh heartbeat in generation `gen`."""
        if not self.enabled:
            return [str(r) for r in range(self.np)]
        gen = self.generation() if gen is None else gen
        timeout = timeout or self.heartbeat_timeout
        now = time.time()
        alive = []
        for mid in self._member_ids(gen):
            raw = self._store.get(f"elastic/gen/{gen}/members/{mid}")
            if raw is not None and now - float(raw) < timeout:
                alive.append(mid)
        return sorted(alive)

    def _member_ids(self, gen):
        """Enumerate ids announced in `gen`: read the atomic slot counter,
        then each slot key — no read-modify-write, so concurrent announces
        can never drop a member."""
        raw = self._store.get(f"elastic/gen/{gen}/roster_slots")
        if raw is None:
            return []
        nslots = _store_int(raw)
        ids = []
        for s in range(1, nslots + 1):
            v = self._store.get(f"elastic/gen/{gen}/roster/{s}")
            if v:
                ids.append(v.decode())
        return sorted(set(ids))

    def announce(self, gen=None):
        """Claim an atomic roster slot for this member in generation `gen`
        (idempotent per generation; duplicate slots dedupe by member id)."""
        if not self.enabled:
            return
        gen = self.generation() if gen is None else gen
        if gen in self._announced_gens:
            return
        self._announced_gens.add(gen)
        slot = self._store.add(f"elastic/gen/{gen}/roster_slots", 1)
        self._store.set(f"elastic/gen/{gen}/roster/{slot}", self.member_id.encode())

    # -- legacy round-1 API (kept: launcher + tests use it) -----------------
    def start_heartbeat(self, interval=2.0):
        self.heartbeat_interval = interval
        self.register()

    def alive_ranks(self, timeout=10.0):
        if not self.enabled:
            return list(range(self.np))
        alive = self.alive_members(timeout=timeout)
        out = []
        for m in alive:
            try:
                out.append(int(m))
            except ValueError:
                out.append(m)
        return out

    def should_restart(self):
        return self.enabled and len(self.alive_members()) < self.np

    # -- re-rendezvous ------------------------------------------------------
    def membership_changed(self, known_generation) -> bool:
        return self.generation() != known_generation

    def wait_generation_change(self, known_generation, timeout=30.0):
        """Block until the generation moves past `known_generation`."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            g = self.generation()
            if g != known_generation:
                return g
            time.sleep(self.heartbeat_interval / 2)
        return known_generation

    def rerendezvous(self):
        """Join the current generation and obtain a dense new rank.

        Returns (new_rank, new_world, generation). The world size is
        frozen by the coordinator (`freeze_world`); callers rebuild their
        mesh from it and resume from the last checkpoint — the
        capability the reference drives through manager.py:462 _match +
        pod re-launch.
        """
        gen = self.generation()
        self.announce(gen)
        self.register(generation=gen)
        new_rank = self._store.add(f"elastic/gen/{gen}/rank", 1) - 1
        deadline = time.time() + 30.0
        while time.time() < deadline:
            raw = self._store.get(f"elastic/gen/{gen}/world")
            if raw:
                return new_rank, _store_int(raw), gen
            time.sleep(0.05)
        raise TimeoutError(
            f"rerendezvous: coordinator never froze elastic/gen/{gen}/world "
            f"— coordinator lost during membership change?")

    def freeze_world(self, world, gen=None):
        """Coordinator: fix the world size for a generation."""
        gen = self.generation() if gen is None else gen
        self._store.set(f"elastic/gen/{gen}/world", str(world).encode())

    # -- scale-out ----------------------------------------------------------
    def request_join(self):
        """A (re)joining member asks the supervisor to grow the world at
        the next re-rendezvous (manager.py scale-out: a pod re-registers
        and the job restarts with the larger world). The supervisor's
        store is the launcher's PADDLE_ELASTIC_ENDPOINT."""
        if not self.enabled:
            return 0
        return self._store.add("elastic/join_requests", 1)

    def pending_join_requests(self) -> int:
        if not self.enabled:
            return 0
        raw = self._store.get("elastic/join_requests")
        return _store_int(raw) if raw else 0

    def consume_join_requests(self, count):
        """Supervisor: mark `count` join requests as honored."""
        if self.enabled:
            self._store.add("elastic/join_requests", -int(count))

    def exit(self, completed=True):
        self._stop = True
        if self._hb is not None:
            self._hb.join(timeout=3)
            self._hb = None
        if self._store is not None:
            self._store.close()
            self._store = None
