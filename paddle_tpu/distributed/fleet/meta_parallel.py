"""Fleet meta-parallel wrappers (parity: fleet/meta_parallel/*).

`PipelineLayer` (pp_layers.py:258) keeps the reference's LayerDesc-based
stage partitioning API. Execution is TPU-native: the whole step compiles to
one SPMD program; stage placement is expressed as parameter sharding over
the "pp" mesh axis. The compiled 1F1B-equivalent microbatch schedule (scan
+ ppermute over "pp") lives in `paddle_tpu.distributed.pipeline` and is
used by the flagship transformer family; arbitrary user PipelineLayers run
as a straight-line program (correctness path) — XLA still overlaps compute
across microbatches via its own scheduling.
"""
from __future__ import annotations

import functools

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential


class LayerDesc:
    """Deferred layer construction (pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Stage-shared layer (e.g. tied embeddings, pp_layers.py SharedLayerDesc)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Parity: pp_layers.py:258. Builds all LayerDescs and partitions them
    into `num_stages` segments (`_stage_bounds`). Execution currently runs
    the straight-line correctness path (all params replicated over "pp");
    compiled stage placement + microbatch scheduling is provided by
    `paddle_tpu.distributed.pipeline` for models that opt in."""

    def __init__(
        self,
        layers,
        num_stages=None,
        topology=None,
        loss_fn=None,
        seg_method="uniform",
        recompute_interval=0,
        **kwargs,
    ):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self._shared = {}

        built = []
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared:
                    layer = self._shared[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.layer_name] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, None))
            else:
                raise TypeError(f"bad layer desc {desc!r}")
        self.run_function = built
        self._layers = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._stage_bounds = self._partition(len(built), self._num_stages, seg_method)

    @staticmethod
    def _partition(n, stages, seg_method):
        bounds = np.linspace(0, n, stages + 1).round().astype(int).tolist()
        return bounds

    def get_stage_from_index(self, idx):
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    def forward(self, x):
        for i, (layer, ffn) in enumerate(self.run_function):
            if ffn is not None:
                x = ffn(layer, x)
            elif isinstance(layer, Layer) or callable(layer):
                x = layer(x)
        return x


class _FleetModelWrapper(Layer):
    """fleet.distributed_model result: dispatches train_batch through the
    compiled hybrid step (model.py:143-170 dispatch parity)."""

    def __init__(self, model, hcg, strategy):
        super().__init__()
        self._inner = model
        self._hcg = hcg
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kwargs):
        return self._inner(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_inner"], name)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        """PipelineParallel.train_batch parity (pipeline_parallel.py:940):
        one compiled step over the hybrid mesh."""
        from ..parallel_step import ShardedTrainStep

        if self._train_step is None:
            inner = self._inner

            if loss_fn is None:
                def default_fn(*batch):
                    x, y = batch
                    out = inner(x)
                    lf = getattr(inner, "_loss_fn", None)
                    if lf is None:
                        raise ValueError("pass loss_fn= to train_batch")
                    return lf(out, y)
                fn = default_fn
            else:
                def fn(*batch):
                    x, y = batch
                    return loss_fn(inner(x), y)

            # ZeRO-1/2 marks from group_sharded_parallel: shard param-shaped
            # optimizer slots over the "sharding" axis
            level = getattr(optimizer, "_group_sharded_level", None)
            self._train_step = ShardedTrainStep(
                inner,
                fn,
                optimizer,
                mesh=self._hcg.mesh,
                shard_opt_states=level in ("os", "os_g", "p_g_os"),
            )
        loss = self._train_step(*data)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


class TensorParallel(_FleetModelWrapper):
    pass


class SegmentParallel(_FleetModelWrapper):
    pass


class PipelineParallel(_FleetModelWrapper):
    pass
