"""Fleet meta-parallel wrappers (parity: fleet/meta_parallel/*).

`PipelineLayer` (pp_layers.py:258) keeps the reference's LayerDesc-based
stage partitioning API. Execution is TPU-native: the whole step compiles to
one SPMD program; stage placement is expressed as parameter sharding over
the "pp" mesh axis. The compiled 1F1B-equivalent microbatch schedule (scan
+ ppermute over "pp") lives in `paddle_tpu.distributed.pipeline` and is
used by the flagship transformer family; arbitrary user PipelineLayers run
as a straight-line program (correctness path) — XLA still overlaps compute
across microbatches via its own scheduling.
"""
from __future__ import annotations

import functools

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential


class LayerDesc:
    """Deferred layer construction (pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Stage-shared layer (e.g. tied embeddings, pp_layers.py SharedLayerDesc)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Parity: pp_layers.py:258. Builds all LayerDescs and partitions them
    into `num_stages` segments (`_stage_bounds`). Execution currently runs
    the straight-line correctness path (all params replicated over "pp");
    compiled stage placement + microbatch scheduling is provided by
    `paddle_tpu.distributed.pipeline` for models that opt in."""

    def __init__(
        self,
        layers,
        num_stages=None,
        topology=None,
        loss_fn=None,
        seg_method="uniform",
        recompute_interval=0,
        **kwargs,
    ):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self._shared = {}

        built = []
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared:
                    layer = self._shared[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.layer_name] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, None))
            else:
                raise TypeError(f"bad layer desc {desc!r}")
        self.run_function = built
        self._layers = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._stage_bounds = self._partition(len(built), self._num_stages, seg_method)
        self._uniform_cache = None
        self._num_micro = None  # microbatches for the compiled schedule

    @staticmethod
    def _partition(n, stages, seg_method):
        bounds = np.linspace(0, n, stages + 1).round().astype(int).tolist()
        return bounds

    def get_stage_from_index(self, idx):
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    # -- compiled pipeline execution ------------------------------------
    def _mesh_pp(self):
        from ...distributed.auto_parallel import get_mesh
        from . import get_fleet_mesh

        mesh = get_fleet_mesh() or get_mesh()
        if mesh is None or "pp" not in mesh.dim_names:
            return None, 1
        return mesh, mesh.get_dim_size("pp")

    def _run_segment(self, s, x):
        """Apply stages [bounds[s], bounds[s+1]) to Tensor x."""
        lo, hi = self._stage_bounds[s], self._stage_bounds[s + 1]
        for layer, ffn in self.run_function[lo:hi]:
            if ffn is not None:
                x = ffn(layer, x)
            else:
                x = layer(x)
        return x

    def _segments_uniform(self, x):
        """True when the compiled ring schedule can serve this layer: every
        stage maps the activation to the same aval AND no stage mutates a
        buffer (the schedule's scan cannot thread per-tick buffer writes
        back out — BatchNorm-style layers take the straight-line path)."""
        import jax

        from ...core.tensor import Tensor

        if self._uniform_cache is not None:
            return self._uniform_cache
        try:
            aval = jax.ShapeDtypeStruct(tuple(x.shape), x._data.dtype)
            state = self.state_dict()
            names = sorted(state)
            state_avals = [
                jax.ShapeDtypeStruct(tuple(state[n].shape),
                                     state[n]._data.dtype) for n in names]
            # every probe runs under _swap_state so a stage that writes its
            # buffers only ever touches trace-local tracers (restored on exit)
            for s in range(self._num_stages):
                def seg_probe(flat, a, s=s):
                    with self._swap_state(dict(zip(names, flat))):
                        return self._run_segment(s, Tensor(a))._data

                out = jax.eval_shape(seg_probe, state_avals, aval)
                if (tuple(out.shape) != tuple(aval.shape)
                        or out.dtype != aval.dtype):
                    self._uniform_cache = False
                    return False

            # buffer-mutation probe: run the whole forward once abstractly
            # and see whether any state entry was reassigned
            flag = [False]

            def probe(flat, a):
                sw = dict(zip(names, flat))
                with self._swap_state(sw) as mut:
                    t = Tensor(a)
                    for s in range(self._num_stages):
                        t = self._run_segment(s, t)
                flag[0] = flag[0] or any(
                    mut.get(n) is not sw[n] for n in sw)
                return t._data

            jax.eval_shape(probe, state_avals, aval)
            self._uniform_cache = not flag[0]
            return self._uniform_cache
        except Exception:
            self._uniform_cache = False
            return False

    def forward(self, x):
        mesh, pp = self._mesh_pp()
        n_micro = self._num_micro or pp
        if (pp > 1 and self._num_stages == pp
                and n_micro >= pp and x.shape[0] % n_micro == 0
                and self._segments_uniform(x)):
            return self._forward_pipelined(x, mesh, pp)
        for s in range(self._num_stages):
            x = self._run_segment(s, x)
        return x

    def _forward_pipelined(self, x, mesh, pp):
        """Compiled ring schedule for arbitrary (shape-uniform) stages.

        Heterogeneous stage programs are selected per device with
        ``lax.switch`` on the pp axis index; all parameters travel into the
        shard_map replicated over "pp" (stage placement of memory is the
        stacked-decoder path's job — this is the generic-correctness one;
        reference slot: pipeline_parallel.py:242 1F1B for any PipelineLayer).
        """
        import jax
        from jax.sharding import PartitionSpec as P

        from ...core.tensor import Tensor
        from ..pipeline import microbatch, pipeline_schedule, unmicrobatch

        state = self.state_dict()
        names = sorted(state)
        flat = [state[n]._data for n in names]
        n_micro = self._num_micro or pp

        def body(flat_params, x_mb):
            # mark params varying over pp: each device consumes them through
            # a DIFFERENT switch branch, and pcast's transpose is the psum
            # that routes every stage's weight cotangent home (without it the
            # vma invariance analysis drops non-zero-stage grads)
            flat_params = [jax.lax.pcast(a, "pp", to="varying")
                           for a in flat_params]

            def make_branch(s):
                def branch(params, a):
                    # params as explicit operands (not closure): the switch
                    # transpose then routes weight cotangents through the
                    # branch each device actually executed
                    with self._swap_state(dict(zip(names, params))):
                        return self._run_segment(s, Tensor(a))._data
                return branch

            branches = [make_branch(s) for s in range(pp)]

            def stage_fn(a):
                idx = jax.lax.axis_index("pp")
                return jax.lax.switch(idx, branches, tuple(flat_params), a)

            return pipeline_schedule(stage_fn, x_mb, pp)

        out = jax.shard_map(
            body, mesh=mesh.jax_mesh,
            in_specs=(P(), P()),
            out_specs=P(),
            axis_names={"pp"},
        )(flat, microbatch(x._data, n_micro))
        return Tensor(unmicrobatch(out))


class _FleetModelWrapper(Layer):
    """fleet.distributed_model result: dispatches train_batch through the
    compiled hybrid step (model.py:143-170 dispatch parity)."""

    def __init__(self, model, hcg, strategy):
        super().__init__()
        self._inner = model
        self._hcg = hcg
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kwargs):
        return self._inner(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_inner"], name)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        """PipelineParallel.train_batch parity (pipeline_parallel.py:940):
        one compiled step over the hybrid mesh."""
        from ..parallel_step import ShardedTrainStep

        if self._train_step is None:
            inner = self._inner

            if loss_fn is None:
                def default_fn(*batch):
                    x, y = batch
                    out = inner(x)
                    lf = getattr(inner, "_loss_fn", None)
                    if lf is None:
                        raise ValueError("pass loss_fn= to train_batch")
                    return lf(out, y)
                fn = default_fn
            else:
                def fn(*batch):
                    x, y = batch
                    return loss_fn(inner(x), y)

            # ZeRO-1/2 marks from group_sharded_parallel: shard param-shaped
            # optimizer slots over the "sharding" axis
            level = getattr(optimizer, "_group_sharded_level", None)
            self._train_step = ShardedTrainStep(
                inner,
                fn,
                optimizer,
                mesh=self._hcg.mesh,
                shard_opt_states=level in ("os", "os_g", "p_g_os"),
            )
        loss = self._train_step(*data)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


class TensorParallel(_FleetModelWrapper):
    pass


class SegmentParallel(_FleetModelWrapper):
    pass


class PipelineParallel(_FleetModelWrapper):
    pass
