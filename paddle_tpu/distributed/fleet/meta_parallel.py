"""Fleet meta-parallel wrappers (parity: fleet/meta_parallel/*).

`PipelineLayer` (pp_layers.py:258) keeps the reference's LayerDesc-based
stage partitioning API. Execution is TPU-native: the whole step compiles to
one SPMD program; stage placement is expressed as parameter sharding over
the "pp" mesh axis. The compiled 1F1B-equivalent microbatch schedule (scan
+ ppermute over "pp") lives in `paddle_tpu.distributed.pipeline` and is
used by the flagship transformer family; arbitrary user PipelineLayers run
as a straight-line program (correctness path) — XLA still overlaps compute
across microbatches via its own scheduling.
"""
from __future__ import annotations

import functools

import numpy as np

from ...nn.layer.layers import Layer
from ...nn.layer.container import LayerList, Sequential


class LayerDesc:
    """Deferred layer construction (pp_layers.py LayerDesc)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Stage-shared layer (e.g. tied embeddings, pp_layers.py SharedLayerDesc)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight", *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Parity: pp_layers.py:258. Builds all LayerDescs and partitions them
    into `num_stages` segments (`_stage_bounds`). Execution currently runs
    the straight-line correctness path (all params replicated over "pp");
    compiled stage placement + microbatch scheduling is provided by
    `paddle_tpu.distributed.pipeline` for models that opt in."""

    def __init__(
        self,
        layers,
        num_stages=None,
        topology=None,
        loss_fn=None,
        seg_method="uniform",
        recompute_interval=0,
        **kwargs,
    ):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._num_stages = num_stages or 1
        self._recompute_interval = recompute_interval
        self._shared = {}

        built = []
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared:
                    layer = self._shared[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared[desc.layer_name] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, None))
            else:
                raise TypeError(f"bad layer desc {desc!r}")
        self.run_function = built
        self._layers = LayerList([l for l, _ in built if isinstance(l, Layer)])
        self._stage_bounds = self._partition(len(built), self._num_stages, seg_method)
        self._uniform_cache = None
        self._num_micro = None  # microbatches for the compiled schedule

    @staticmethod
    def _partition(n, stages, seg_method):
        bounds = np.linspace(0, n, stages + 1).round().astype(int).tolist()
        return bounds

    def get_stage_from_index(self, idx):
        for s in range(self._num_stages):
            if self._stage_bounds[s] <= idx < self._stage_bounds[s + 1]:
                return s
        return self._num_stages - 1

    # -- compiled pipeline execution ------------------------------------
    def _mesh_pp(self):
        from . import active_mesh

        mesh = active_mesh()
        if mesh is None or "pp" not in mesh.dim_names:
            return None, 1
        return mesh, mesh.get_dim_size("pp")

    def _run_segment(self, s, x):
        """Apply stages [bounds[s], bounds[s+1]) to Tensor x."""
        lo, hi = self._stage_bounds[s], self._stage_bounds[s + 1]
        for layer, ffn in self.run_function[lo:hi]:
            if ffn is not None:
                x = ffn(layer, x)
            else:
                x = layer(x)
        return x

    def _segments_uniform(self, x, n_micro):
        """Pipeline-compatibility probe. The compiled ring needs every
        INTER-STAGE boundary aval identical (the rotating carry is one
        SPMD value) — but stage 0's INPUT and the last stage's OUTPUT may
        differ freely: branch 0 of the lax.switch consumes the raw input
        (e.g. token ids), and only the last branch fills the (separately
        typed) output buffer. That serves the real embed->blocks->head
        shape. Also rejects buffer-mutating stages (the scan cannot
        thread per-tick buffer writes back out).

        Probes at MICROBATCH granularity (leading dim / n_micro) so the
        returned avals are exactly the ring's carry/output types — stages
        that fold the batch axis into another dim stay consistent, and a
        later call with a different input shape re-probes instead of
        reusing stale avals. Returns (mid_aval, out_aval) when
        pipelinable, None otherwise; cached per (input aval, n_micro)."""
        import jax

        from ...core.tensor import Tensor

        key = (tuple(x.shape), str(x._data.dtype), n_micro)
        if self._uniform_cache is None:
            self._uniform_cache = {}
        if key in self._uniform_cache:
            return self._uniform_cache[key] or None
        try:
            aval = jax.ShapeDtypeStruct(
                (x.shape[0] // n_micro,) + tuple(x.shape[1:]),
                x._data.dtype)
            state = self.state_dict()
            names = sorted(state)
            state_avals = [
                jax.ShapeDtypeStruct(tuple(state[n].shape),
                                     state[n]._data.dtype) for n in names]
            # every probe runs under _swap_state so a stage that writes its
            # buffers only ever touches trace-local tracers (restored on exit)
            cur = aval
            boundary = []           # aval AFTER stage s, s = 0..n-1
            for s in range(self._num_stages):
                def seg_probe(flat, a, s=s):
                    with self._swap_state(dict(zip(names, flat))):
                        return self._run_segment(s, Tensor(a))._data

                cur = jax.eval_shape(seg_probe, state_avals, cur)
                boundary.append(
                    jax.ShapeDtypeStruct(tuple(cur.shape), cur.dtype))
            mids = boundary[:-1]    # the rotating-carry avals
            if mids and any((tuple(m.shape), m.dtype)
                            != (tuple(mids[0].shape), mids[0].dtype)
                            for m in mids):
                self._uniform_cache[key] = False
                return None

            # buffer-mutation probe: run the whole forward once abstractly
            # and see whether any state entry was reassigned
            flag = [False]

            def probe(flat, a):
                sw = dict(zip(names, flat))
                with self._swap_state(sw) as mut:
                    t = Tensor(a)
                    for s in range(self._num_stages):
                        t = self._run_segment(s, t)
                flag[0] = flag[0] or any(
                    mut.get(n) is not sw[n] for n in sw)
                return t._data

            jax.eval_shape(probe, state_avals, aval)
            if flag[0]:
                self._uniform_cache[key] = False
                return None
            mid = mids[0] if mids else boundary[-1]
            self._uniform_cache[key] = (mid, boundary[-1])
            return self._uniform_cache[key]
        except Exception:
            self._uniform_cache[key] = False
            return None

    def _pipelined_avals(self, x):
        """Shared pipelined-path eligibility gate: returns (mesh, pp,
        (mid_aval, out_aval)) when the compiled ring applies, else
        (mesh, pp, None)."""
        mesh, pp = self._mesh_pp()
        n_micro = self._num_micro or pp
        avals = (self._segments_uniform(x, n_micro)
                 if (pp > 1 and self._num_stages == pp and n_micro >= pp
                     and x.shape[0] % n_micro == 0) else None)
        return mesh, pp, avals

    def forward(self, x):
        mesh, pp, avals = self._pipelined_avals(x)
        if avals:
            return self._forward_pipelined(x, mesh, pp, *avals)
        for s in range(self._num_stages):
            x = self._run_segment(s, x)
        return x

    def forward_loss(self, x, labels, loss_fn):
        """Forward + loss with the loss consumed IN-RING on the last
        stage (VERDICT r3 missing-item 6): the head's vocab-sized output
        never crosses the pp ring — only the per-microbatch scalar loss
        is psum-replicated. Reference contrast: stages own their outputs
        and only the last stage computes loss
        (fleet/meta_parallel/pp_layers.py:258, pipeline_parallel.py:940).

        loss_fn(out_tensor, label_tensor) -> scalar Tensor, applied per
        microbatch; the mean over microbatches is returned (equal
        microbatch sizes, so it equals the full-batch mean loss)."""
        mesh, pp, avals = self._pipelined_avals(x)
        if avals:
            losses = self._forward_pipelined(x, mesh, pp, *avals,
                                             labels=labels, loss_fn=loss_fn)
            return losses.mean()
        return loss_fn(self.forward(x), labels)

    # -- stage-partitioned parameter memory ------------------------------
    def _param_stage_map(self):
        """state_dict name -> owning stage index (absent = shared or
        layer-level state, kept replicated)."""
        mapping = {}
        shared_ids = {id(l) for l in self._shared.values()}
        li = 0
        for i, (layer, _) in enumerate(self.run_function):
            if not isinstance(layer, Layer):
                continue
            prefix = f"_layers.{li}."
            li += 1
            if id(layer) in shared_ids:
                continue  # tied across stages -> replicated
            stage = self.get_stage_from_index(i)
            for n in layer.state_dict():
                mapping[prefix + n] = stage
        return mapping

    def shard_stage_parameters(self, mesh=None):
        """ZeRO-3-style striping of every stage-owned parameter over the
        "pp" mesh axis: per-device persistent param memory drops to
        ~total/pp (the reason to use PP at all — reference slot:
        pp_layers.py:258, stages own only their layers). The compiled
        pipeline repacks the stripes into per-stage rows inside the step
        (one XLA reshard), so the lax.switch branches still read only the
        local stage's weights."""
        from ...distributed.auto_parallel import (Replicate, Shard,
                                                  TensorDistAttr)
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if mesh is None:
            mesh, pp = self._mesh_pp()
            if mesh is None:
                return self
        pp = mesh.get_dim_size("pp")
        ax = mesh.dim_names.index("pp")
        mapping = self._param_stage_map()
        state = self.state_dict()
        for name, stage in mapping.items():
            t = state.get(name)
            if t is None:
                continue
            dim = next((d for d, s in enumerate(t.shape) if s % pp == 0),
                       None)
            if dim is None:
                continue  # no divisible dim: stays replicated
            placements = [Replicate() for _ in mesh.dim_names]
            placements[ax] = Shard(dim)
            t._dist_attr = TensorDistAttr(mesh, placements)
            spec = [None] * len(t.shape)
            spec[dim] = "pp"
            t._data = jax.device_put(
                t._data, NamedSharding(mesh.jax_mesh, P(*spec)))
        return self

    def _forward_pipelined(self, x, mesh, pp, mid_aval, out_aval,
                           labels=None, loss_fn=None):
        """Compiled ring schedule for arbitrary stages with uniform
        INTER-STAGE avals; stage 0's input type (token ids) and the last
        stage's output type (logits) may differ — branch 0 of the switch
        consumes the raw microbatch and every branch returns a
        (mid_carry, final_out) pair of which exactly one is real, so the
        rotating carry stays one SPMD type while the embed->blocks->head
        pattern pipelines (round-2 Weak #4).

        Heterogeneous stage programs are selected per device with
        ``lax.switch`` on the pp axis index. Stage-owned parameters are
        PACKED: each stage's params flatten-concat into one row of a
        [pp, L] buffer sharded over "pp", so inside the shard_map every
        device holds exactly its own stage's weights (per-device pipeline
        memory O(total/pp) when combined with `shard_stage_parameters`;
        reference slot: pipeline_parallel.py:242 1F1B for any
        PipelineLayer). Shared/tied params stay replicated operands.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from ...core.tensor import Tensor
        from ..pipeline import microbatch, unmicrobatch

        state = self.state_dict()
        names = sorted(state)
        n_micro = self._num_micro or pp
        stage_of = self._param_stage_map()

        staged = [n for n in names if stage_of.get(n) is not None]
        shared_names = [n for n in names if stage_of.get(n) is None]
        dtypes = sorted({str(state[n]._data.dtype) for n in staged})
        # static packing plan: dtype -> per-stage [(name, offset, size,
        # shape)] + row length
        plan, row_len = {}, {}
        for dt in dtypes:
            per_stage = [[] for _ in range(pp)]
            for n in staged:
                a = state[n]._data
                if str(a.dtype) != dt:
                    continue
                s = stage_of[n]
                off = sum(e[2] for e in per_stage[s])
                per_stage[s].append((n, off, int(np.prod(a.shape) or 1),
                                     tuple(a.shape)))
            plan[dt] = per_stage
            row_len[dt] = max(
                (sum(e[2] for e in st) for st in per_stage), default=0)

        def pack(flat_state):
            packed = {}
            for dt in dtypes:
                rows = []
                for s in range(pp):
                    parts = [flat_state[n].reshape(-1)
                             for n, _, _, _ in plan[dt][s]]
                    row = (jnp.concatenate(parts) if parts
                           else jnp.zeros((0,), dt))
                    pad = row_len[dt] - row.shape[0]
                    if pad:
                        row = jnp.concatenate(
                            [row, jnp.zeros((pad,), row.dtype)])
                    rows.append(row)
                arr = jnp.stack(rows)           # [pp, L]
                packed[dt] = jax.lax.with_sharding_constraint(
                    arr, NamedSharding(mesh.jax_mesh, P("pp")))
            return packed

        flat_all = {n: state[n]._data for n in names}
        shared_flat = [flat_all[n] for n in shared_names]

        mid_mb, out_mb = mid_aval, out_aval   # probe returns mb-sized

        def body(ids, packed, shared, x_mb, lab_mb):
            # shared params consumed by several branches: pcast-varying so
            # the switch transpose psums their cotangents home
            shared = [jax.lax.pcast(a, "pp", to="varying") for a in shared]
            # stage ordinal via sharded iota: lax.axis_index lowers to the
            # PartitionId op this container's XLA rejects (pipeline.py)
            idx = ids[0]

            def make_branch(s):
                def branch(packed_local, shared_ops, x_in, state):
                    params = {}
                    for dt in dtypes:
                        row = packed_local[dt][0]      # local [1, L] row
                        for n, off, size, shape in plan[dt][s]:
                            params[n] = jax.lax.dynamic_slice_in_dim(
                                row, off, size).reshape(shape)
                    params.update(zip(shared_names, shared_ops))
                    seg_in = x_in if s == 0 else state
                    with self._swap_state(params):
                        out = self._run_segment(s, Tensor(seg_in))._data
                    # exactly one of (mid, final) is real per branch; the
                    # placeholder zeros must carry the same pp-varying
                    # annotation as the real outputs (shard_map vma)
                    if s == pp - 1:
                        z = jax.lax.pcast(
                            jnp.zeros(mid_mb.shape, mid_mb.dtype),
                            "pp", to="varying")
                        return (z, out)
                    z = jax.lax.pcast(
                        jnp.zeros(out_mb.shape, out_mb.dtype),
                        "pp", to="varying")
                    return (out, z)
                return branch

            branches = [make_branch(s) for s in range(pp)]

            def stage_fn2(x_in, state):
                return jax.lax.switch(
                    idx, branches, packed, tuple(shared), x_in, state)

            from ..pipeline import pipeline_schedule_hetero

            out_consume = None
            if loss_fn is not None:
                # last-stage-owned output: the per-microbatch loss runs
                # in-ring on the owner stage; only its scalar crosses the
                # closing psum — the vocab-sized head output never moves
                def out_consume(fin, mb_idx):
                    lab = jax.lax.dynamic_index_in_dim(
                        lab_mb, mb_idx, 0, keepdims=False)
                    return loss_fn(Tensor(fin), Tensor(lab))._data

            return pipeline_schedule_hetero(
                stage_fn2, x_mb, pp, mid_mb, out_mb,
                out_consume=out_consume, stage_id=idx)

        lab_arr = (labels._data if loss_fn is not None
                   else jnp.zeros((x.shape[0],), jnp.int32))
        # one jitted ring per program signature: a fresh jax.jit over a
        # fresh closure would re-trace and re-compile on every call
        key = (mesh.jax_mesh, pp, n_micro, loss_fn,
               tuple((n, state[n]._data.shape, str(state[n]._data.dtype))
                     for n in names),
               x._data.shape, str(x._data.dtype),
               lab_arr.shape, str(lab_arr.dtype))
        cache = self.__dict__.setdefault("_ring_jit_cache", {})
        jitted = cache.get(key)
        if jitted is not None:
            cache[key] = cache.pop(key)   # refresh recency: LRU, not FIFO
        else:
            # EVERY live mesh axis joins as MANUAL (replicated specs over
            # the non-pp axes): an auto axis propagating into the region
            # is the IsManualSubgroup partitioner hard-abort on this XLA
            # (the same fix as the grad-reduce region — the ring math is
            # replicated over dp/mp, so per-shard code is unchanged)
            sharded = jax.shard_map(
                body, mesh=mesh.jax_mesh,
                in_specs=(P("pp"), {dt: P("pp") for dt in dtypes}, P(),
                          P(), P()),
                out_specs=P(),
                axis_names=set(mesh.jax_mesh.axis_names),
            )
            # the legacy shard_map has no eager path for regions with auto
            # (non-manual) mesh axes — a fleet mesh always carries its
            # other (possibly size-1) axes, so the ring must run under jit
            # bounded LRU: a fresh-closure loss_fn per call (identity
            # key misses, same cost as the pre-cache behavior) must not
            # grow the cache or evict the hot entries — hits refresh
            # recency above, so next(iter) is the least-recently used
            if len(cache) >= 8:
                cache.pop(next(iter(cache)))
            jitted = cache[key] = jax.jit(sharded)
        from .. import collectives as _coll

        # partial-manual region (pp manual, other fleet axes auto): any
        # shard_activation hint traced inside it is the IsManualSubgroup
        # hard-abort on legacy jax — the region flag makes them skip
        with _coll.manual_grad_region():
            out = jitted(
                jnp.arange(pp, dtype=jnp.int32), pack(flat_all),
                shared_flat, microbatch(x._data, n_micro),
                microbatch(lab_arr, n_micro))
        if loss_fn is not None:
            return Tensor(out)                  # [n_micro] losses
        return Tensor(unmicrobatch(out))


class _FleetModelWrapper(Layer):
    """fleet.distributed_model result: dispatches train_batch through the
    compiled hybrid step (model.py:143-170 dispatch parity)."""

    def __init__(self, model, hcg, strategy):
        super().__init__()
        self._inner = model
        self._hcg = hcg
        self._strategy = strategy
        self._train_step = None

    def forward(self, *args, **kwargs):
        return self._inner(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_inner"], name)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None, loss_fn=None):
        """PipelineParallel.train_batch parity (pipeline_parallel.py:940):
        one compiled step over the hybrid mesh."""
        from ..parallel_step import ShardedTrainStep

        if self._train_step is None:
            inner = self._inner

            if loss_fn is None:
                def default_fn(*batch):
                    x, y = batch
                    lf = getattr(inner, "_loss_fn", None)
                    if lf is None:
                        raise ValueError("pass loss_fn= to train_batch")
                    if hasattr(inner, "forward_loss"):
                        return inner.forward_loss(x, y, lf)
                    return lf(inner(x), y)
                fn = default_fn
            elif hasattr(inner, "forward_loss"):
                # PipelineLayer: consume the loss in-ring on the owner
                # stage — the head's output never crosses the pp ring
                def fn(*batch):
                    x, y = batch
                    return inner.forward_loss(x, y, loss_fn)
            else:
                def fn(*batch):
                    x, y = batch
                    return loss_fn(inner(x), y)

            # ZeRO-1/2 marks from group_sharded_parallel: shard param-shaped
            # optimizer slots over the "sharding" axis
            level = getattr(optimizer, "_group_sharded_level", None)
            self._train_step = ShardedTrainStep(
                inner,
                fn,
                optimizer,
                mesh=self._hcg.mesh,
                shard_opt_states=level in ("os", "os_g", "p_g_os"),
            )
        loss = self._train_step(*data)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss


class TensorParallel(_FleetModelWrapper):
    pass


class SegmentParallel(_FleetModelWrapper):
    pass


class PipelineParallel(_FleetModelWrapper):
    pass
