"""fleet.utils — recompute (activation checkpointing) and helpers.

Parity: `python/paddle/distributed/fleet/utils/__init__.py` (recompute),
`python/paddle/distributed/fleet/recompute/recompute.py`.

TPU-native: the reference saves/restores RNG state and re-runs forward in
backward by hand; here recompute is `jax.checkpoint` — XLA rematerialises
the segment during the backward pass, trading FLOPs for HBM. Works on both
execution paths: under `jit`/`TrainStep` the remat annotation rides the
whole-graph trace; in eager mode the checkpointed segment is recorded as a
single tape op whose VJP rematerialises.
"""
from __future__ import annotations

from contextlib import nullcontext

import jax
from jax import tree_util

from .... import framework
from ....core.dispatch import apply_op, _is_tensor
from ....core.tensor import Tensor


def recompute(function, *args, **kwargs):
    """Checkpoint `function(*args, **kwargs)`: don't store its activations.

    `function` should be a Layer (or a bound method of one) so its parameters
    are threaded through explicitly and receive gradients on the eager tape.
    Plain closures still work under the jit path (jax remat differentiates
    through closed-over tracers) but lose eager-tape param grads.
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)

    from ....nn.layer.layers import Layer

    if isinstance(function, Layer):
        layer, call = function, function
    else:
        layer = getattr(function, "__self__", None)
        layer = layer if isinstance(layer, Layer) else None
        call = function

    entries = layer.state_dict() if layer is not None else {}
    names = list(entries)

    leaves, treedef = tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    tpos = [i for i, l in enumerate(leaves) if _is_tensor(l)]

    def run(state_arrays, tensor_arrays):
        buf = list(leaves)
        for p, a in zip(tpos, tensor_arrays):
            buf[p] = Tensor(a)
        a2, k2 = tree_util.tree_unflatten(treedef, buf)
        ctx = (
            layer._swap_state(dict(zip(names, state_arrays)))
            if layer is not None
            else nullcontext()
        )
        with ctx, framework.no_grad():
            out = call(*a2, **k2)
        return tree_util.tree_map(
            lambda t: t._data if _is_tensor(t) else t,
            out,
            is_leaf=_is_tensor,
        )

    ckpt = jax.checkpoint(run)
    state_tensors = [entries[n] for n in names]
    tensor_args = [leaves[i] for i in tpos]
    return apply_op(ckpt, state_tensors, tensor_args, _op_name="recompute")


class LocalFS:
    """Parity stub: fleet.utils.LocalFS (file-system helper)."""

    def ls_dir(self, path):
        import os

        return [], os.listdir(path) if os.path.isdir(path) else []

    def is_exist(self, path):
        import os

        return os.path.exists(path)

    def mkdirs(self, path):
        import os

        os.makedirs(path, exist_ok=True)


class HDFSClient(LocalFS):
    """HDFS access shim (fleet/utils/fs.py HDFSClient): LocalFS semantics
    behind the same API (no hadoop runtime in the TPU image); hdfs://
    URIs raise with guidance. Extends LocalFS so the two filesystem
    classes cannot diverge."""

    def __init__(self, hadoop_home=None, configs=None):
        self.hadoop_home = hadoop_home

    @staticmethod
    def _check(path):
        if str(path).startswith("hdfs://"):
            raise RuntimeError(
                "no hadoop runtime in the TPU image — stage data to local "
                "disk or GCS-fuse mounts and pass filesystem paths")
        return str(path)

    def is_exist(self, path):
        return super().is_exist(self._check(path))

    def is_dir(self, path):
        import os

        return os.path.isdir(self._check(path))

    def is_file(self, path):
        import os

        return os.path.isfile(self._check(path))

    def ls_dir(self, path):
        import os

        p = self._check(path)
        entries = os.listdir(p) if os.path.isdir(p) else []
        dirs = [e for e in entries if os.path.isdir(os.path.join(p, e))]
        files = [e for e in entries if not os.path.isdir(os.path.join(p, e))]
        return dirs, files

    def mkdirs(self, path):
        return super().mkdirs(self._check(path))

    def delete(self, path):
        import os
        import shutil

        p = self._check(path)
        if os.path.isdir(p):
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.remove(p)

    def upload(self, local_path, fs_path, **kw):
        import shutil

        shutil.copy(local_path, self._check(fs_path))

    def download(self, fs_path, local_path, **kw):
        import shutil

        shutil.copy(self._check(fs_path), local_path)


class DistributedInfer:
    """PS-mode distributed inference helper (fleet/utils/__init__.py):
    pulls the latest table values before serving."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        from .. import _ps_state

        if _ps_state.get("client") is None:
            from .. import init_worker

            init_worker()

    def get_dist_infer_program(self):
        return self._main
