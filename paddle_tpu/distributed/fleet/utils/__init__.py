"""fleet.utils — recompute (activation checkpointing) and helpers.

Parity: `python/paddle/distributed/fleet/utils/__init__.py` (recompute),
`python/paddle/distributed/fleet/recompute/recompute.py`.

TPU-native: the reference saves/restores RNG state and re-runs forward in
backward by hand; here recompute is `jax.checkpoint` — XLA rematerialises
the segment during the backward pass, trading FLOPs for HBM. Works on both
execution paths: under `jit`/`TrainStep` the remat annotation rides the
whole-graph trace; in eager mode the checkpointed segment is recorded as a
single tape op whose VJP rematerialises.
"""
from __future__ import annotations

from contextlib import nullcontext

import jax
from jax import tree_util

from .... import framework
from ....core.dispatch import apply_op, _is_tensor
from ....core.tensor import Tensor


def recompute(function, *args, **kwargs):
    """Checkpoint `function(*args, **kwargs)`: don't store its activations.

    `function` should be a Layer (or a bound method of one) so its parameters
    are threaded through explicitly and receive gradients on the eager tape.
    Plain closures still work under the jit path (jax remat differentiates
    through closed-over tracers) but lose eager-tape param grads.
    """
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)

    from ....nn.layer.layers import Layer

    if isinstance(function, Layer):
        layer, call = function, function
    else:
        layer = getattr(function, "__self__", None)
        layer = layer if isinstance(layer, Layer) else None
        call = function

    entries = layer.state_dict() if layer is not None else {}
    names = list(entries)

    leaves, treedef = tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    tpos = [i for i, l in enumerate(leaves) if _is_tensor(l)]

    def run(state_arrays, tensor_arrays):
        buf = list(leaves)
        for p, a in zip(tpos, tensor_arrays):
            buf[p] = Tensor(a)
        a2, k2 = tree_util.tree_unflatten(treedef, buf)
        ctx = (
            layer._swap_state(dict(zip(names, state_arrays)))
            if layer is not None
            else nullcontext()
        )
        with ctx, framework.no_grad():
            out = call(*a2, **k2)
        return tree_util.tree_map(
            lambda t: t._data if _is_tensor(t) else t,
            out,
            is_leaf=_is_tensor,
        )

    ckpt = jax.checkpoint(run)
    state_tensors = [entries[n] for n in names]
    tensor_args = [leaves[i] for i in tpos]
    return apply_op(ckpt, state_tensors, tensor_args, _op_name="recompute")


class LocalFS:
    """Parity stub: fleet.utils.LocalFS (file-system helper)."""

    def ls_dir(self, path):
        import os

        return [], os.listdir(path) if os.path.isdir(path) else []

    def is_exist(self, path):
        import os

        return os.path.exists(path)

    def mkdirs(self, path):
        import os

        os.makedirs(path, exist_ok=True)
