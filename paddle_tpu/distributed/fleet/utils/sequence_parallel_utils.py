"""Megatron-style sequence parallelism (parity:
`fleet/utils/sequence_parallel_utils.py:85-137,429,564`).

The reference implements SP with explicit PyLayers (ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp) around Column/RowParallelLinear. TPU-native
redesign: SP is a *sharding pattern*, not hand-written collectives — the
non-matmul region keeps activations sharded over the sequence dim on the
"mp" axis; constraining the matmul input to seq-replicated makes XLA emit
the all_gather, and constraining the row-output to seq-sharded turns the
partial-sum into a reduce_scatter. Same comm volume as Megatron-SP, but
scheduled/fused by XLA and overlapped over ICI.

Layout convention: activations are [batch, seq, hidden] (the reference's SP
utils assume [s, b, h]; batch-major is the TPU/GSPMD-friendly layout, and
paddle_tpu TP layers are batch-major throughout).
"""
from __future__ import annotations

from ...auto_parallel import shard_activation
from .. import get_fleet_mesh


def _data_axes(mesh):
    return tuple(
        a for a in ("dp", "sharding", "sep")
        if a in mesh.dim_names and mesh.get_dim_size(a) > 1
    )


def _spec(mesh, seq):
    """PartitionSpec for [batch, seq, ...]: batch over data axes, seq per arg."""
    from jax.sharding import PartitionSpec

    d = _data_axes(mesh)
    return PartitionSpec(d if d else None, seq)


def _mp_active(mesh):
    return mesh is not None and "mp" in mesh.dim_names and mesh.get_dim_size("mp") > 1


def scatter(x, axis=1):
    """ScatterOp: split the sequence dim over mp (identity bwd = gather)."""
    mesh = get_fleet_mesh()
    if not _mp_active(mesh):
        return x
    return shard_activation(x, mesh=mesh, spec=_spec(mesh, "mp"))


def all_gather(x, axis=1):
    """GatherOp/AllGatherOp: materialise the full sequence dim."""
    mesh = get_fleet_mesh()
    if not _mp_active(mesh):
        return x
    return shard_activation(x, mesh=mesh, spec=_spec(mesh, None))


def reduce_scatter(x, axis=1):
    """ReduceScatterOp: resolve an mp-partial sum directly into seq shards.

    Under GSPMD this is the same sharding constraint as :func:`scatter` —
    XLA lowers the partial-sum + seq-shard combination to a reduce-scatter.
    """
    return scatter(x, axis)


class ScatterOp:
    @staticmethod
    def apply(x, axis=1):
        return scatter(x, axis)


class GatherOp:
    @staticmethod
    def apply(x, axis=1):
        return all_gather(x, axis)


class AllGatherOp:
    @staticmethod
    def apply(x):
        return all_gather(x)


class ReduceScatterOp:
    @staticmethod
    def apply(x):
        return reduce_scatter(x)


def mark_as_sequence_parallel_parameter(parameter):
    """Parity: sequence_parallel_utils.py:148 — marks params whose grads the
    reference must all-reduce over mp by hand (LayerNorm params in the SP
    region). Under GSPMD those params are replicated and their grads are
    reduced by the compiler, so this is metadata only."""
    parameter.sequence_parallel = True
    return parameter


def create_fused_allreduce_gradient_hooks(model, accumulation_steps=1):
    """No-op under GSPMD: gradient reduction is compiled into the step."""
    return []


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """No-op under GSPMD (see mark_as_sequence_parallel_parameter)."""
    return None


def is_fused_matmul_bias_supported():
    return True


class ColumnSequenceParallelLinear:
    """Constructed via fleet.mpu.ColumnParallelLinear(sequence_parallel=True)."""

    def __new__(cls, in_features, out_features, **kwargs):
        from ..mpu import ColumnParallelLinear

        kwargs["sequence_parallel"] = True
        return ColumnParallelLinear(in_features, out_features, **kwargs)


class RowSequenceParallelLinear:
    def __new__(cls, in_features, out_features, **kwargs):
        from ..mpu import RowParallelLinear

        kwargs["sequence_parallel"] = True
        return RowParallelLinear(in_features, out_features, **kwargs)
