"""Tensor-parallel (Megatron-style) layers.

Parity surface: `fleet/layers/mpu/mp_layers.py:49,336,543,744`
(VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear /
ParallelCrossEntropy) and the comm prims of `mp_ops.py` — redesigned for
GSPMD: instead of calling `_c_identity/_c_concat/_mp_allreduce` by hand,
each layer (1) creates its parameter annotated with a `Shard` placement
over the "mp" mesh axis and (2) constrains activation shardings where the
Megatron pattern requires it. XLA then inserts exactly the collectives the
reference hand-writes (identity fwd + allreduce bwd for column, allreduce
fwd for row), fused into the surrounding matmuls.

Sequence parallel (`sequence_parallel_utils.py`): with
``sequence_parallel=True`` the layer keeps the non-matmul activations
sharded over the sequence dim on the "mp" axis, so XLA emits
all_gather before the first TP matmul and reduce_scatter after the last —
the exact Megatron-SP communication pattern.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ... import framework
from ...core.tensor import Tensor
from ...nn import functional as F
from ...nn.layer.layers import Layer
from ..auto_parallel import Replicate, Shard, TensorDistAttr, shard_activation
from . import get_fleet_mesh


def _annotate(param, tensor_dim):
    """Attach an mp-axis Shard placement (resolved to a real sharding when
    the train step places params on the mesh)."""
    mesh = get_fleet_mesh()
    if mesh is None or "mp" not in mesh.dim_names or mesh.get_dim_size("mp") == 1:
        return param
    placements = [Replicate() for _ in mesh.dim_names]
    placements[mesh.dim_names.index("mp")] = Shard(tensor_dim)
    param._dist_attr = TensorDistAttr(mesh, placements)
    return param


def _replicate_spec(mesh):
    """Spec for gather_output: batch stays on data axes, rest replicated."""
    from .utils.sequence_parallel_utils import _spec

    return _spec(mesh, None)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self._dim = embedding_dim
        self.weight = _annotate(
            self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr),
            tensor_dim=0,
        )

    def forward(self, x):
        out = F.embedding(x, self.weight)
        mesh = get_fleet_mesh()
        if mesh is not None and "mp" in mesh.dim_names and mesh.get_dim_size("mp") > 1:
            # spmd rule `embedding` (spmd_rules.py, tested in
            # test_spmd_rules.py::TestEmbeddingRule): vocab-sharded table ->
            # output partial over mp; the resolved placement (replicated
            # over mp, batch on the data axes) binds the masked-lookup +
            # allreduce plan — the c_embedding pattern (embedding.cc:30) —
            # instead of letting propagation all_gather the sharded table.
            from ..spmd_rules import constraints_enabled

            if constraints_enabled():
                out = shard_activation(out, mesh=mesh, spec=_replicate_spec(mesh))
        return out


class ColumnParallelLinear(Layer):
    """W:[in,out] sharded on out over mp (mp_layers.py:336)."""

    def __init__(
        self,
        in_features,
        out_features,
        weight_attr=None,
        has_bias=True,
        gather_output=True,
        fuse_matmul_bias=False,
        mp_group=None,
        sequence_parallel=False,
        name=None,
    ):
        super().__init__()
        self.gather_output = gather_output
        self.sequence_parallel = sequence_parallel
        self.weight = _annotate(
            self.create_parameter([in_features, out_features], attr=weight_attr),
            tensor_dim=1,
        )
        self.bias = (
            _annotate(self.create_parameter([out_features], is_bias=True), tensor_dim=0)
            if has_bias
            else None
        )

    def forward(self, x):
        if self.sequence_parallel:
            # incoming activation is seq-sharded over mp; constraining the
            # matmul input to seq-replicated makes XLA emit the SP all_gather
            from .utils import sequence_parallel_utils as spu

            x = spu.all_gather(x)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            mesh = get_fleet_mesh()
            if mesh is not None:
                out = shard_activation(out, mesh=mesh, spec=_replicate_spec(mesh))
        return out


class RowParallelLinear(Layer):
    """W:[in,out] sharded on in over mp; output carries the mp partial sum,
    resolved by XLA as the Megatron allreduce (mp_layers.py:543)."""

    def __init__(
        self,
        in_features,
        out_features,
        weight_attr=None,
        has_bias=True,
        input_is_parallel=False,
        fuse_matmul_bias=False,
        mp_group=None,
        sequence_parallel=False,
        name=None,
    ):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.sequence_parallel = sequence_parallel
        self.weight = _annotate(
            self.create_parameter([in_features, out_features], attr=weight_attr),
            tensor_dim=0,
        )
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.sequence_parallel:
            # constrain the mp-partial output to seq-sharded: XLA lowers the
            # pending sum + seq split to one reduce_scatter (Megatron-SP bwd
            # of the gather, sequence_parallel_utils.py:564)
            from .utils import sequence_parallel_utils as spu

            out = spu.reduce_scatter(out)
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over an mp-sharded vocab logit (mp_layers.py:744).

    GSPMD computes the log-softmax reduction over the sharded vocab dim with
    the same comm pattern the reference's c_softmax_with_cross_entropy
    kernel implements (max + sum allreduce over mp)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(
            input, label, reduction="none", ignore_index=self.ignore_index
        )


# ---------------------------------------------------------------------------
# per-group RNG for dropout under TP (fleet/layers/mpu/random.py:34)
# ---------------------------------------------------------------------------
class RNGStatesTracker:
    """Named RNG states so TP ranks can draw the same (global) or different
    (local, e.g. dropout inside the sharded block) randomness.

    jax redesign: a named state is a PRNG key folded from the global seed;
    "local" streams additionally fold in the mp coordinate at trace time via
    axis_index — here, single-controller GSPMD means dropout masks are
    generated globally and sharded like their activations, which already
    gives per-shard-distinct, reproducible randomness. The tracker therefore
    keeps per-name independent key streams."""

    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        import jax

        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = jax.random.key(seed)

    @contextlib.contextmanager
    def rng_state(self, name="model-parallel-rng"):
        if name not in self.states_:
            import zlib

            import jax

            # stable digest: hash() is salted per-process and would give
            # multi-controller processes divergent dropout streams
            self.states_[name] = jax.random.key(zlib.crc32(name.encode()))
        import jax

        key = self.states_[name]
        key, sub = jax.random.split(key)
        self.states_[name] = key
        with framework.rng_key_scope(sub):
            yield


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random

    seed = seed if seed is not None else random.randint(0, 2**31 - 1)
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add("global_seed", seed)
    _RNG_STATE_TRACKER.add("model-parallel-rng", seed + 1024)


# mp_ops comm-prim parity (mp_ops.py:76-272): under GSPMD these are
# sharding annotations, not eager collectives.
def _c_identity(x, group=None):
    return x


def _c_concat(x, group=None):
    mesh = get_fleet_mesh()
    if mesh is None:
        return x
    return shard_activation(x, [Replicate() for _ in mesh.dim_names], mesh=mesh)


def _c_split(x, group=None):
    mesh = get_fleet_mesh()
    if mesh is None:
        return x
    placements = [Replicate() for _ in mesh.dim_names]
    placements[mesh.dim_names.index("mp")] = Shard(x.ndim - 1)
    return shard_activation(x, placements, mesh=mesh)


def _mp_allreduce(x, group=None, use_calc_stream=True, use_model_parallel=True):
    return x  # partial sums are resolved by GSPMD at the next use


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity (mp_ops.py:786) — returns the
    corresponding parallel layer applied to x."""
    if operation == "linear":
        layer = (
            ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                 has_bias=bias_attr is not False, gather_output=gather_out)
            if axis == 1
            else RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                   has_bias=bias_attr is not False)
        )
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported operation {operation}")
