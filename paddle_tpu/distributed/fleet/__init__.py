"""paddle.distributed.fleet — hybrid-parallel training surface.

Parity: `python/paddle/distributed/fleet` (fleet.init `fleet.py:218`,
distributed_model `model.py:33`, distributed_optimizer `fleet.py:1448`,
DistributedStrategy `base/distributed_strategy.py:284`).

TPU-native: `fleet.init` builds one ProcessMesh with axes
(pp, dp, sharding, sep, mp) instead of creating NCCL communicators; the
wrappers annotate parameter/batch shardings and hand the step to
`paddle_tpu.distributed.ShardedTrainStep`, where GSPMD emits the
collectives the reference's reducers/meta-optimizers issue by hand.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np


class DistributedStrategy:
    """Parity: fleet.DistributedStrategy (strategy proto wrapper)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.without_graph_optimization = True

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(value)
            self.__dict__["hybrid_configs"] = merged
        else:
            self.__dict__[key] = value


_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
    "mesh": None,
}


def get_fleet_mesh():
    return _fleet_state["mesh"]


def active_mesh():
    """The mesh governing compilation right now: the fleet topology if
    fleet.init built one, else the auto-parallel global mesh. The ONE
    definition of that precedence — model/functional/hapi sites all
    consult this instead of re-encoding it."""
    from ..auto_parallel import get_mesh

    return _fleet_state["mesh"] or get_mesh()


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """fleet.init — build the hybrid topology mesh (fleet.py:218)."""
    from .. import init_parallel_env
    from .topology import build_hybrid_mesh

    strategy = strategy or DistributedStrategy()
    cfg = strategy.hybrid_configs
    init_parallel_env()
    topo, hcg, mesh = build_hybrid_mesh(
        dp=cfg.get("dp_degree", 1),
        mp=cfg.get("mp_degree", 1),
        pp=cfg.get("pp_degree", 1),
        sharding=cfg.get("sharding_degree", 1),
        sep=cfg.get("sep_degree", 1),
    )
    _fleet_state.update(
        initialized=True, strategy=strategy, hcg=hcg, mesh=mesh
    )
    from ..auto_parallel import set_mesh

    set_mesh(mesh)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def _reset_for_tests():
    """Drop fleet/global mesh state so a test can re-init a new topology."""
    from ..auto_parallel import set_mesh

    _fleet_state.update(initialized=False, strategy=None, hcg=None, mesh=None)
    set_mesh(None)


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def _get_strategy():
    return _fleet_state["strategy"]


def worker_index():
    return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def worker_num():
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def distributed_model(model):
    """Wrap for the active parallel mode (model.py:143-170).

    dp-only -> DataParallel semantics (batch sharded over dp);
    mp -> parameters already carry mp placements (TP layers);
    pp -> PipelineParallel wrapper with the compiled ppermute schedule.
    All paths share ShardedTrainStep; the wrapper records which axes shard
    the batch and where parameters live.
    """
    from .meta_parallel import _FleetModelWrapper

    return _FleetModelWrapper(model, _fleet_state["hcg"], _fleet_state["strategy"])


def distributed_optimizer(optimizer, strategy=None):
    """Parity: fleet.py:1448 + HybridParallelOptimizer — under GSPMD the
    cross-group grad reduction/clip is part of the compiled step, so this
    returns the optimizer annotated with the hybrid context."""
    optimizer._hcg = _fleet_state["hcg"]
    optimizer._fleet_strategy = strategy or _fleet_state["strategy"]
    return optimizer


from .utils import recompute  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from .mpu import (  # noqa: E402,F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from .meta_parallel import (  # noqa: E402,F401
    LayerDesc,
    PipelineLayer,
    SharedLayerDesc,
)

# namespace parity: fleet.meta_parallel / fleet.layers.mpu import paths
from . import mpu as _mpu_module  # noqa: E402
import sys as _sys

_sys.modules[__name__ + ".layers"] = _sys.modules[__name__]
_sys.modules[__name__ + ".layers.mpu"] = _mpu_module


from .topology import CommunicateTopology, HybridCommunicateGroup  # noqa: F401


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self, *a, **k):
        pass

    def is_worker(self):
        return True

    def is_server(self):
        return False

    def is_first_worker(self):
        return worker_index() == 0

    def worker_num(self):
        return worker_num()

    def worker_index(self):
        return worker_index()


class PaddleCloudRoleMaker(RoleMakerBase):
    """parity: fleet/base/role_maker.py:548 — reads the PADDLE_* env.

    PS mode: TRAINING_ROLE=PSERVER|TRAINER selects the role;
    PADDLE_PSERVER_NUMS / PADDLE_TRAINERS_NUM size the two groups."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()

    def is_worker(self):
        return self._is_collective or self._role == "TRAINER"

    def is_server(self):
        return not self._is_collective and self._role == "PSERVER"

    def server_num(self):
        return int(os.environ.get("PADDLE_PSERVER_NUMS", 1))

    def server_index(self):
        return int(os.environ.get("PADDLE_PSERVER_ID", 0))


# -- PS mode (parity: fleet.init_server/run_server/init_worker over the
#    distributed/ps tables — see distributed/ps/__init__.py) ---------------
_ps_state = {"server": None, "client": None, "stop": None}


def init_server(model_dir=None, **kwargs):
    from ..ps import get_global_server

    server = get_global_server()
    if model_dir:
        server.load(model_dir)
    _ps_state["server"] = server
    return server


def run_server():
    import threading

    from ..ps import serve_forever

    _ps_state["stop"] = threading.Event()
    serve_forever(_ps_state["stop"])


def init_worker(servers=None, **kwargs):
    """`servers`: rpc server names or in-process PSServer objects; default
    = the process-global server (single-node mode)."""
    from ..ps import PSClient, get_global_server

    _ps_state["client"] = PSClient(servers or [get_global_server()])
    return _ps_state["client"]


def get_ps_client():
    if _ps_state["client"] is None:
        raise RuntimeError("fleet.init_worker() has not been called")
    return _ps_state["client"]


def stop_worker():
    client = _ps_state["client"]
    if client is not None:
        try:
            client.stop_servers()   # remote stop verb unparks run_server
        except Exception:
            pass
    _ps_state["client"] = None
    if _ps_state["stop"] is not None:
        _ps_state["stop"].set()


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, init_gloo=False, **kwargs):
        self._kwargs = kwargs


class UtilBase:
    """parity: fleet/base/util_factory.py UtilBase."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from .. import all_reduce as _ar
        import paddle_tpu as _p

        t = _p.to_tensor(np.asarray(input))
        _ar(t)
        return t.numpy()

    def barrier(self, comm_world="worker"):
        from .. import barrier as _b

        _b()

    def all_gather(self, input, comm_world="worker"):
        return [input] * worker_num()

    def get_file_shard(self, files):
        n, i = worker_num(), worker_index()
        return files[i::n]

    def print_on_rank(self, message, rank_id=0):
        if worker_index() == rank_id:
            print(message)


util = UtilBase()


class Fleet:
    """Object form of the module-level fleet API (fleet/fleet.py Fleet)."""

    def __init__(self):
        self.util = util

    def init(self, *a, **k):
        return init(*a, **k)

    def is_first_worker(self):
        return worker_index() == 0

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_worker(self):
        return PaddleCloudRoleMaker(
            is_collective=_get_strategy() is not None).is_worker()

    def is_server(self):
        return PaddleCloudRoleMaker(
            is_collective=_get_strategy() is not None).is_server()

    def barrier_worker(self):
        from .. import barrier as _b

        _b()

    def distributed_model(self, model):
        return distributed_model(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    # PS mode
    def init_server(self, *a, **k):
        return init_server(*a, **k)

    def run_server(self):
        return run_server()

    def init_worker(self, *a, **k):
        return init_worker(*a, **k)

    def stop_worker(self):
        return stop_worker()


class MultiSlotDataGenerator:
    """PS streaming data generator protocol (fleet/data_generator)."""

    def set_batch(self, batch_size):
        self._batch = batch_size

    def run_from_stdin(self):
        import sys

        for line in sys.stdin:
            for out in self.generate_sample(line)():
                sys.stdout.write(self._format(out))

    def _format(self, sample):
        parts = []
        for name, values in sample:
            parts.append(f"{len(values)} " + " ".join(map(str, values)))
        return " ".join(parts) + "\n"

    def generate_sample(self, line):
        raise NotImplementedError


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    pass
