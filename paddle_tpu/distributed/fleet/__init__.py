"""paddle.distributed.fleet — hybrid-parallel training surface.

Parity: `python/paddle/distributed/fleet` (fleet.init `fleet.py:218`,
distributed_model `model.py:33`, distributed_optimizer `fleet.py:1448`,
DistributedStrategy `base/distributed_strategy.py:284`).

TPU-native: `fleet.init` builds one ProcessMesh with axes
(pp, dp, sharding, sep, mp) instead of creating NCCL communicators; the
wrappers annotate parameter/batch shardings and hand the step to
`paddle_tpu.distributed.ShardedTrainStep`, where GSPMD emits the
collectives the reference's reducers/meta-optimizers issue by hand.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np


class DistributedStrategy:
    """Parity: fleet.DistributedStrategy (strategy proto wrapper)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.without_graph_optimization = True

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(value)
            self.__dict__["hybrid_configs"] = merged
        else:
            self.__dict__[key] = value


_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
    "mesh": None,
}


def get_fleet_mesh():
    return _fleet_state["mesh"]


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """fleet.init — build the hybrid topology mesh (fleet.py:218)."""
    from .. import init_parallel_env
    from .topology import build_hybrid_mesh

    strategy = strategy or DistributedStrategy()
    cfg = strategy.hybrid_configs
    init_parallel_env()
    topo, hcg, mesh = build_hybrid_mesh(
        dp=cfg.get("dp_degree", 1),
        mp=cfg.get("mp_degree", 1),
        pp=cfg.get("pp_degree", 1),
        sharding=cfg.get("sharding_degree", 1),
        sep=cfg.get("sep_degree", 1),
    )
    _fleet_state.update(
        initialized=True, strategy=strategy, hcg=hcg, mesh=mesh
    )
    from ..auto_parallel import set_mesh

    set_mesh(mesh)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def _reset_for_tests():
    """Drop fleet/global mesh state so a test can re-init a new topology."""
    from ..auto_parallel import set_mesh

    _fleet_state.update(initialized=False, strategy=None, hcg=None, mesh=None)
    set_mesh(None)


def get_hybrid_communicate_group():
    return _fleet_state["hcg"]


def _get_strategy():
    return _fleet_state["strategy"]


def worker_index():
    return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def worker_num():
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def distributed_model(model):
    """Wrap for the active parallel mode (model.py:143-170).

    dp-only -> DataParallel semantics (batch sharded over dp);
    mp -> parameters already carry mp placements (TP layers);
    pp -> PipelineParallel wrapper with the compiled ppermute schedule.
    All paths share ShardedTrainStep; the wrapper records which axes shard
    the batch and where parameters live.
    """
    from .meta_parallel import _FleetModelWrapper

    return _FleetModelWrapper(model, _fleet_state["hcg"], _fleet_state["strategy"])


def distributed_optimizer(optimizer, strategy=None):
    """Parity: fleet.py:1448 + HybridParallelOptimizer — under GSPMD the
    cross-group grad reduction/clip is part of the compiled step, so this
    returns the optimizer annotated with the hybrid context."""
    optimizer._hcg = _fleet_state["hcg"]
    optimizer._fleet_strategy = strategy or _fleet_state["strategy"]
    return optimizer


from .utils import recompute  # noqa: E402,F401
from . import utils  # noqa: E402,F401
from .mpu import (  # noqa: E402,F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
from .meta_parallel import (  # noqa: E402,F401
    LayerDesc,
    PipelineLayer,
    SharedLayerDesc,
)

# namespace parity: fleet.meta_parallel / fleet.layers.mpu import paths
from . import mpu as _mpu_module  # noqa: E402
import sys as _sys

_sys.modules[__name__ + ".layers"] = _sys.modules[__name__]
_sys.modules[__name__ + ".layers.mpu"] = _mpu_module
