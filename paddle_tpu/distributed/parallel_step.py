"""ShardedTrainStep: the hybrid-parallel compiled train step.

This is where the reference's whole distributed-runtime stack (EagerReducer
bucketed allreduce `reducer.h:88`, sharding-stage optimizers
`dygraph_sharding_optimizer.py:54`, hybrid grad clip
`hybrid_parallel_optimizer.py:275`, reshard insertion) collapses into one
TPU-native mechanism: parameters/optimizer slots/batch are placed on the
hybrid mesh with NamedShardings, the (forward, loss, backward, update)
program is jit-compiled once, and GSPMD emits every collective —
dp gradient psum where grads are partial over "dp", reduce-scatter/
all-gather where states are sharded over "sharding" (ZeRO), TP collectives
where mp placements require them — scheduled and fused by XLA over ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter, Tensor
from ..jit import TrainStep, _unwrap_tensors
from .auto_parallel import (
    ProcessMesh,
    Replicate,
    Shard,
    placements_to_spec,
)

P = PartitionSpec


def _param_sharding(mesh: ProcessMesh, p) -> NamedSharding:
    if getattr(p, "_dist_attr", None) is not None:
        return NamedSharding(
            mesh.jax_mesh,
            placements_to_spec(p._dist_attr.process_mesh, p._dist_attr.placements),
        )
    return NamedSharding(mesh.jax_mesh, P())


def _batch_spec(mesh: ProcessMesh, arr) -> NamedSharding:
    """Shard batch dim 0 over every data-ish axis present (dp, sharding, sep)."""
    axes = [a for a in ("dp", "sharding", "sep") if a in mesh.dim_names and mesh.get_dim_size(a) > 1]
    if not axes or arr.ndim == 0:
        return NamedSharding(mesh.jax_mesh, P())
    total = int(np.prod([mesh.get_dim_size(a) for a in axes]))
    if arr.shape[0] % total != 0:
        return NamedSharding(mesh.jax_mesh, P())
    return NamedSharding(mesh.jax_mesh, P(tuple(axes)))


class ShardedTrainStep(TrainStep):
    """TrainStep over a hybrid ProcessMesh.

    Placement protocol:
    - params with `_dist_attr` (TP layers, ZeRO-3 marks) -> their placements;
      others replicated.
    - optimizer slots follow their parameter (same shape) or replicate
      (scalars); with `shard_opt_states=True` (ZeRO-1/2) param-shaped slots
      are additionally sharded over the "sharding" axis.
    - batch tensors shard dim 0 over dp×sharding×sep.
    """

    def __init__(self, model, train_fn, optimizer, mesh: ProcessMesh,
                 scaler=None, shard_opt_states=False, shard_vocab_head=None):
        super().__init__(model, train_fn, optimizer, scaler)
        self.mesh = mesh
        self.shard_opt_states = shard_opt_states
        # vocab-sharded LM head ("last-stage-sharded pipeline output"):
        # an axis name places the tied head's vocab dim over that tp axis
        # via model.shard_lm_head, routing the loss through the
        # scalars-per-token sharded CE (models/gpt.py compute_loss). None
        # defers to PTPU_SHARDED_HEAD=<axis|1> (1 -> "mp"); default off so
        # existing mp meshes keep their lowered programs bit-stable.
        if shard_vocab_head is None:
            import os

            env = os.environ.get("PTPU_SHARDED_HEAD", "")
            shard_vocab_head = ("mp" if env == "1"
                                else env if env not in ("", "0") else None)
        self.shard_vocab_head = shard_vocab_head
        self._placed = False

    # -- placement ---------------------------------------------------------
    def _place_model(self):
        ax = self.shard_vocab_head
        if (ax and ax in self.mesh.dim_names
                and self.mesh.get_dim_size(ax) > 1
                and hasattr(self.model, "shard_lm_head")):
            self.model.shard_lm_head(self.mesh, axis=ax)
        entries = self.model.state_dict()
        for name, t in entries.items():
            sh = _param_sharding(self.mesh, t)
            t._data = jax.device_put(t._data, sh)
        self._placed = True

    def _slot_sharding(self, pname, p_sharding, slot_arr, param_shape):
        if tuple(slot_arr.shape) == tuple(param_shape):
            if self.shard_opt_states:
                spec = list(p_sharding.spec) + [None] * (
                    len(param_shape) - len(p_sharding.spec)
                )
                taken = {a for e in spec if e for a in (e if isinstance(e, tuple) else (e,))}
                if (
                    "sharding" in self.mesh.dim_names
                    and self.mesh.get_dim_size("sharding") > 1
                    and "sharding" not in taken
                    and len(param_shape) > 0
                ):
                    size = self.mesh.get_dim_size("sharding")
                    for d in range(len(param_shape)):
                        if param_shape[d] % size == 0:
                            cur = spec[d]
                            spec[d] = (
                                ("sharding",) if cur is None
                                else (tuple(cur) if isinstance(cur, tuple) else (cur,)) + ("sharding",)
                            )
                            if not isinstance(spec[d], tuple) or len(spec[d]) == 1:
                                spec[d] = spec[d][0] if isinstance(spec[d], tuple) else spec[d]
                            break
                return NamedSharding(self.mesh.jax_mesh, P(*spec))
            return p_sharding
        return NamedSharding(self.mesh.jax_mesh, P())

    def _place_opt_state(self, params):
        entries = self.model.state_dict()
        for name, slots in self._opt_state.items():
            p = entries[name]
            psh = _param_sharding(self.mesh, p)
            for sname, arr in slots.items():
                slots[sname] = jax.device_put(
                    arr, self._slot_sharding(name, psh, arr, p._data.shape)
                )

    def _place_batch(self, raw_batch):
        placed = []
        for arr in raw_batch:
            if isinstance(arr, jax.ShapeDtypeStruct):
                # planner path (aot_compile over avals): device_put would
                # reject an abstract value — carry the same sharding a
                # real batch would get so the lowered program matches
                placed.append(jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype,
                    sharding=_batch_spec(self.mesh, arr)))
            elif hasattr(arr, "ndim") and arr.ndim >= 1:
                placed.append(jax.device_put(arr, _batch_spec(self.mesh, arr)))
            else:
                placed.append(arr)
        return tuple(placed)

    def _prepare_batch(self, raw_batch):
        """memory_stats hook: mirror __call__'s placement so the lowered
        program matches the one real steps run (sharded batch, placed
        model/opt state)."""
        if not self._placed:
            self._place_model()
        if self._opt_state is None:
            entries = self.model.state_dict()
            params = {n: entries[n]._data for n in self._param_names}
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        return self._place_batch(raw_batch)

    # -- step --------------------------------------------------------------
    def __call__(self, *batch):
        # same instrumentation contract as TrainStep.__call__ (docs/
        # TELEMETRY.md train_step_seconds/train_steps_total) — the
        # override must not drop it for exactly the multi-chip runs
        # where step timing matters most
        from ..jit import _TRAIN_STEP_SECONDS, _TRAIN_STEPS
        from .. import telemetry as _telemetry

        model_label = (type(self.model).__name__,)
        _TRAIN_STEPS.inc(labels=model_label)
        with _telemetry.timer(_TRAIN_STEP_SECONDS, labels=model_label):
            return self._sharded_call(*batch)

    def _sharded_call(self, *batch):
        if not self._placed:
            self._place_model()
        first_state = self._opt_state is None
        if self._compiled is None:
            self._build()
        entries = self.model.state_dict()
        params = {n: entries[n]._data for n in self._param_names}
        if first_state:
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        raw_batch = self._place_batch(_unwrap_tensors(batch))
        buffers = {n: entries[n]._data for n in self._buffer_names}
        lr = self.optimizer.get_lr()
        guard_arr = self._guard_operand()
        from .. import framework

        key_arr = framework.next_rng_key()
        # no ambient mesh context needed: every input carries an explicit
        # NamedSharding, and constraints inside the program name their mesh.
        loss, new_params, new_buffers, self._opt_state, health = \
            self._compiled(
                params, buffers, self._opt_state, lr, guard_arr, key_arr,
                raw_batch
            )
        self._last_health = health
        for n, arr in new_params.items():
            entries[n]._data = arr
        for n, arr in new_buffers.items():
            entries[n]._data = arr
        self.optimizer._step_count += 1
        return Tensor(loss)


# ---------------------------------------------------------------------------
# ZeRO / group-sharded marks (parity: group_sharded_parallel,
# dygraph_sharding_optimizer.py:54, group_sharded_stage{2,3}.py)
# ---------------------------------------------------------------------------
def shard_model_parameters(model, mesh: ProcessMesh, axis="sharding"):
    """ZeRO-3: give every parameter a Shard(0) placement over `axis`
    (falls back to the first divisible dim, else stays replicated)."""
    from .auto_parallel import TensorDistAttr

    size = mesh.get_dim_size(axis)
    ax_idx = mesh.dim_names.index(axis)
    for _, p in model.named_parameters():
        if p._dist_attr is not None:
            taken = any(
                isinstance(pl, Shard) and i == ax_idx
                for i, pl in enumerate(p._dist_attr.placements)
            )
            if taken:
                continue
            placements = list(p._dist_attr.placements)
        else:
            placements = [Replicate() for _ in mesh.dim_names]
        shard_dims = {pl.dim for pl in placements if isinstance(pl, Shard)}
        for d in range(p._data.ndim):
            if d not in shard_dims and p._data.shape[d] % size == 0:
                placements[ax_idx] = Shard(d)
                break
        p._dist_attr = TensorDistAttr(mesh, placements)
    return model


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, **kwargs):
    """Parity: paddle.distributed.sharding.group_sharded_parallel.

    level: "os" (stage1) | "os_g" (stage2) | "p_g_os" (stage3).
    Returns (model, optimizer, scaler) with sharding marks applied; the
    actual partitioning happens when ShardedTrainStep places state on the
    mesh (stage1/2 -> shard_opt_states, stage3 -> param placements).
    """
    from .auto_parallel import get_mesh

    mesh = get_mesh()
    if mesh is None:
        from .fleet import get_fleet_mesh

        mesh = get_fleet_mesh()
    if mesh is None:
        raise RuntimeError("call fleet.init or set_mesh before group_sharded_parallel")
    if level == "p_g_os":
        shard_model_parameters(model, mesh)
    optimizer._group_sharded_level = level
    return model, optimizer, scaler
