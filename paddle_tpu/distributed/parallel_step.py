"""ShardedTrainStep: the hybrid-parallel compiled train step.

This is where the reference's whole distributed-runtime stack (EagerReducer
bucketed allreduce `reducer.h:88`, sharding-stage optimizers
`dygraph_sharding_optimizer.py:54`, hybrid grad clip
`hybrid_parallel_optimizer.py:275`, reshard insertion) collapses into one
TPU-native mechanism: parameters/optimizer slots/batch are placed on the
hybrid mesh with NamedShardings, the (forward, loss, backward, update)
program is jit-compiled once, and GSPMD emits every collective —
dp gradient psum where grads are partial over "dp", reduce-scatter/
all-gather where states are sharded over "sharding" (ZeRO), TP collectives
where mp placements require them — scheduled and fused by XLA over ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter, Tensor
from ..jit import TrainStep, _unwrap_tensors
from .auto_parallel import (
    ProcessMesh,
    Replicate,
    Shard,
    placements_to_spec,
)

P = PartitionSpec


def _param_sharding(mesh: ProcessMesh, p) -> NamedSharding:
    if getattr(p, "_dist_attr", None) is not None:
        return NamedSharding(
            mesh.jax_mesh,
            placements_to_spec(p._dist_attr.process_mesh, p._dist_attr.placements),
        )
    return NamedSharding(mesh.jax_mesh, P())


def _batch_spec(mesh: ProcessMesh, arr) -> NamedSharding:
    """Shard batch dim 0 over every data-ish axis present (dp, sharding, sep)."""
    axes = [a for a in ("dp", "sharding", "sep") if a in mesh.dim_names and mesh.get_dim_size(a) > 1]
    if not axes or arr.ndim == 0:
        return NamedSharding(mesh.jax_mesh, P())
    total = int(np.prod([mesh.get_dim_size(a) for a in axes]))
    if arr.shape[0] % total != 0:
        return NamedSharding(mesh.jax_mesh, P())
    return NamedSharding(mesh.jax_mesh, P(tuple(axes)))


class ShardedTrainStep(TrainStep):
    """TrainStep over a hybrid ProcessMesh.

    Placement protocol:
    - params with `_dist_attr` (TP layers, ZeRO-3 marks) -> their placements;
      others replicated.
    - optimizer slots follow their parameter (same shape) or replicate
      (scalars); with `shard_opt_states=True` (ZeRO-1/2) param-shaped slots
      are additionally sharded over the "sharding" axis.
    - batch tensors shard dim 0 over dp×sharding×sep.
    """

    def __init__(self, model, train_fn, optimizer, mesh: ProcessMesh,
                 scaler=None, shard_opt_states=False, shard_vocab_head=None):
        super().__init__(model, train_fn, optimizer, scaler)
        self.mesh = mesh
        self.shard_opt_states = shard_opt_states
        # vocab-sharded LM head ("last-stage-sharded pipeline output"):
        # an axis name places the tied head's vocab dim over that tp axis
        # via model.shard_lm_head, routing the loss through the
        # scalars-per-token sharded CE (models/gpt.py compute_loss). None
        # defers to PTPU_SHARDED_HEAD=<axis|1> (1 -> "mp"); default off so
        # existing mp meshes keep their lowered programs bit-stable.
        if shard_vocab_head is None:
            import os

            env = os.environ.get("PTPU_SHARDED_HEAD", "")
            shard_vocab_head = ("mp" if env == "1"
                                else env if env not in ("", "0") else None)
        self.shard_vocab_head = shard_vocab_head
        self._placed = False
        # dp-grad reduce plan (distributed/collectives): resolved at
        # first trace (knobs are build-time, never per call) — None
        # keeps the pre-PR GSPMD grad psum byte-for-byte
        self._reduce_plan = None
        self._reduce_plan_ready = False

    # -- placement ---------------------------------------------------------
    def _place_model(self):
        ax = self.shard_vocab_head
        if (ax and ax in self.mesh.dim_names
                and self.mesh.get_dim_size(ax) > 1
                and hasattr(self.model, "shard_lm_head")):
            self.model.shard_lm_head(self.mesh, axis=ax)
        entries = self.model.state_dict()
        for name, t in entries.items():
            sh = _param_sharding(self.mesh, t)
            t._data = jax.device_put(t._data, sh)
        self._placed = True

    def _slot_sharding(self, pname, p_sharding, slot_arr, param_shape):
        if tuple(slot_arr.shape) == tuple(param_shape):
            if self.shard_opt_states:
                spec = list(p_sharding.spec) + [None] * (
                    len(param_shape) - len(p_sharding.spec)
                )
                taken = {a for e in spec if e for a in (e if isinstance(e, tuple) else (e,))}
                if (
                    "sharding" in self.mesh.dim_names
                    and self.mesh.get_dim_size("sharding") > 1
                    and "sharding" not in taken
                    and len(param_shape) > 0
                ):
                    size = self.mesh.get_dim_size("sharding")
                    for d in range(len(param_shape)):
                        if param_shape[d] % size == 0:
                            cur = spec[d]
                            spec[d] = (
                                ("sharding",) if cur is None
                                else (tuple(cur) if isinstance(cur, tuple) else (cur,)) + ("sharding",)
                            )
                            if not isinstance(spec[d], tuple) or len(spec[d]) == 1:
                                spec[d] = spec[d][0] if isinstance(spec[d], tuple) else spec[d]
                            break
                return NamedSharding(self.mesh.jax_mesh, P(*spec))
            return p_sharding
        return NamedSharding(self.mesh.jax_mesh, P())

    def _place_opt_state(self, params):
        entries = self.model.state_dict()
        for name, slots in self._opt_state.items():
            p = entries[name]
            psh = _param_sharding(self.mesh, p)
            for sname, arr in slots.items():
                slots[sname] = jax.device_put(
                    arr, self._slot_sharding(name, psh, arr, p._data.shape)
                )

    def _place_batch(self, raw_batch):
        placed = []
        for arr in raw_batch:
            if isinstance(arr, jax.ShapeDtypeStruct):
                # planner path (aot_compile over avals): device_put would
                # reject an abstract value — carry the same sharding a
                # real batch would get so the lowered program matches
                placed.append(jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype,
                    sharding=_batch_spec(self.mesh, arr)))
            elif hasattr(arr, "ndim") and arr.ndim >= 1:
                placed.append(jax.device_put(arr, _batch_spec(self.mesh, arr)))
            else:
                placed.append(arr)
        return tuple(placed)

    def _prepare_batch(self, raw_batch):
        """memory_stats hook: mirror __call__'s placement so the lowered
        program matches the one real steps run (sharded batch, placed
        model/opt state)."""
        if not self._placed:
            self._place_model()
        if self._opt_state is None:
            entries = self.model.state_dict()
            params = {n: entries[n]._data for n in self._param_names}
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        return self._place_batch(raw_batch)

    # -- quantized/bucketed dp-grad reduce (distributed/collectives) -------
    def _ensure_reduce_plan(self):
        """Resolve (once) whether this step owns its dp grad reduce.

        Falls back to the inherited GSPMD program (plan None) whenever
        the restructure is unsafe or worthless on this runtime: master
        knob off, checkify debug mode, a live mesh axis outside
        {dp, sharding, mp} (pipeline/sep/ep kernels open their own
        manual regions, which cannot nest inside ours on this XLA), a
        param placement on a data axis (ZeRO-3), a vocab-sharded head
        (same nesting limit), or no gradient big enough to quantize."""
        if self._reduce_plan_ready:
            return self._reduce_plan
        self._reduce_plan_ready = True
        self._reduce_plan = None
        from ..utils.flags import get_flags
        from . import collectives

        if not collectives.quant_collectives_enabled():
            return None
        if get_flags("check_nan_inf")["check_nan_inf"]:
            return None
        mp_live = ("mp" in self.mesh.dim_names
                   and self.mesh.get_dim_size("mp") > 1)
        if self.shard_vocab_head and mp_live:
            # the vocab-sharded CE opens its own mp shard_map island
            return None
        if collectives.tp_seam_mode() == "fused" and mp_live:
            # explicit seam forcing: the seam islands win the one manual
            # region this XLA allows (docs/COMMS.md precedence)
            return None
        entries = self.model.state_dict()
        taken = set()
        for n in self._param_names:
            da = getattr(entries[n], "_dist_attr", None)
            if da is None:
                continue
            for ax_name, pl in zip(da.process_mesh.dim_names, da.placements):
                if isinstance(pl, Shard):
                    taken.add(ax_name)
        if taken & {"dp", "sharding"}:
            # ZeRO-3: a param placement on a DATA axis means the forward
            # must all-gather params inside the region, and gather with
            # manual subgroups is exactly the lowering this XLA rejects
            # (docs/COMMS.md runtime limits) — those placements stay
            # with GSPMD end to end, on every data axis
            return None
        named = [(n, tuple(entries[n]._data.shape),
                  entries[n]._data.dtype) for n in self._param_names]
        self._reduce_plan = collectives.build_grad_reduce_plan(
            named, self.mesh)
        return self._reduce_plan

    def comms_plan(self):
        """The active grad-reduce plan (None = pre-PR GSPMD path) — the
        bench/dryrun "comms" block embeds its summary()."""
        return self._reduce_plan if self._reduce_plan_ready else None

    def _value_and_grads(self, make_loss_of, params, buffers, key_arr,
                         batch):
        # checkify debug rebuilds (FLAGS_check_nan_inf flipped after the
        # first build) must not reuse an engaged plan: checkify cannot
        # instrument through the manual region
        if getattr(self, "_checkified", False):
            return super()._value_and_grads(make_loss_of, params, buffers,
                                            key_arr, batch)
        plan = self._ensure_reduce_plan()
        if plan is None:
            return super()._value_and_grads(make_loss_of, params, buffers,
                                            key_arr, batch)
        import jax as _jax
        from jax import shard_map

        from . import collectives

        axes = plan.axes
        total = int(np.prod([self.mesh.get_dim_size(a) for a in axes]))

        def leaf_spec(arr):
            # mirror _batch_spec: dim 0 over the data axes when it splits
            if (hasattr(arr, "ndim") and arr.ndim >= 1
                    and arr.shape[0] % total == 0):
                return P(axes)
            return P()

        batch_specs = tuple(leaf_spec(a) for a in batch)
        pspecs = {n: P() for n in params}
        bspecs = {n: P() for n in buffers}
        nbspecs = {n: P() for n in self._buffer_names}

        def per_shard(params, buffers, key_arr, shard_id, *batch):
            # per-shard loss over the LOCAL batch rows; grads are the
            # per-rank partials the bucketed/quantized reduce combines.
            # NOTE the dp-mean here averages per-shard means — identical
            # to the global mean when shards hold equal valid-token
            # counts (a masked-loss skew shifts weighting by at most the
            # count imbalance; docs/COMMS.md)
            #
            # per-shard RNG stream: fold the shard ordinal into the step
            # key so dropout masks are independent across data shards
            # (the pre-PR global trace drew one mask per GLOBAL row; the
            # same key on every shard would tile one local mask pattern
            # across the batch). lax.axis_index lowers to PartitionId,
            # which this XLA rejects — the ordinal rides in as a
            # P(axes)-sharded iota instead (the sharded-CE trick).
            key = _jax.random.fold_in(key_arr, shard_id[0])
            loss_of = make_loss_of(buffers, key, batch)
            (loss, new_buffers), grads = _jax.value_and_grad(
                loss_of, has_aux=True)(params)
            loss = _jax.lax.pmean(loss, axes)
            # dp-consistent buffers: a batch-updated float buffer (BN-
            # style running stats) is computed from the LOCAL shard here
            # where the pre-PR program saw the global batch — pmean makes
            # the stored value deterministic and exact for linear
            # running-stat updates (mean of per-shard means). Replicated
            # untouched buffers pass through bitwise for power-of-two
            # shard counts; non-float buffers stay local (docs/COMMS.md).
            new_buffers = {
                n: (_jax.lax.pmean(v, axes)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v)
                for n, v in new_buffers.items()}
            grads = collectives.reduce_grads(grads, plan, mean=True)
            return loss, new_buffers, grads

        shard_ids = jnp.arange(total, dtype=jnp.int32)
        with collectives.manual_grad_region():
            loss, new_buffers, grads = shard_map(
                per_shard, mesh=self.mesh.jax_mesh,
                in_specs=(pspecs, bspecs, P(), P(axes)) + batch_specs,
                out_specs=(P(), nbspecs, pspecs),
                check_vma=False, axis_names=set(axes),
            )(params, buffers, key_arr, shard_ids, *batch)
        return (loss, new_buffers), grads

    # -- step --------------------------------------------------------------
    def __call__(self, *batch):
        # same instrumentation contract as TrainStep.__call__ (docs/
        # TELEMETRY.md train_step_seconds/train_steps_total) — the
        # override must not drop it for exactly the multi-chip runs
        # where step timing matters most
        from ..jit import _TRAIN_STEP_SECONDS, _TRAIN_STEPS
        from .. import telemetry as _telemetry

        model_label = (type(self.model).__name__,)
        _TRAIN_STEPS.inc(labels=model_label)
        with _telemetry.timer(_TRAIN_STEP_SECONDS, labels=model_label):
            return self._sharded_call(*batch)

    def _sharded_call(self, *batch):
        if not self._placed:
            self._place_model()
        first_state = self._opt_state is None
        if self._compiled is None:
            self._build()
        entries = self.model.state_dict()
        params = {n: entries[n]._data for n in self._param_names}
        if first_state:
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        raw_batch = self._place_batch(_unwrap_tensors(batch))
        buffers = {n: entries[n]._data for n in self._buffer_names}
        lr = self.optimizer.get_lr()
        guard_arr = self._guard_operand()
        from .. import framework

        key_arr = framework.next_rng_key()
        # no ambient mesh context needed: every input carries an explicit
        # NamedSharding, and constraints inside the program name their mesh.
        loss, new_params, new_buffers, self._opt_state, health = \
            self._dispatch_compiled(
                params, buffers, self._opt_state, lr, guard_arr, key_arr,
                raw_batch
            )
        self._last_health = health
        for n, arr in new_params.items():
            entries[n]._data = arr
        for n, arr in new_buffers.items():
            entries[n]._data = arr
        self.optimizer._step_count += 1
        # comms accounting: one tick per executed step with the plan's
        # static payload split (exact vs int8) — the counters behind the
        # bench "comms" block (docs/COMMS.md)
        from .collectives import note_grad_reduce

        note_grad_reduce(self._reduce_plan)
        return Tensor(loss)


# ---------------------------------------------------------------------------
# ZeRO / group-sharded marks (parity: group_sharded_parallel,
# dygraph_sharding_optimizer.py:54, group_sharded_stage{2,3}.py)
# ---------------------------------------------------------------------------
def shard_model_parameters(model, mesh: ProcessMesh, axis="sharding"):
    """ZeRO-3: give every parameter a Shard(0) placement over `axis`
    (falls back to the first divisible dim, else stays replicated)."""
    from .auto_parallel import TensorDistAttr

    size = mesh.get_dim_size(axis)
    ax_idx = mesh.dim_names.index(axis)
    for _, p in model.named_parameters():
        if p._dist_attr is not None:
            taken = any(
                isinstance(pl, Shard) and i == ax_idx
                for i, pl in enumerate(p._dist_attr.placements)
            )
            if taken:
                continue
            placements = list(p._dist_attr.placements)
        else:
            placements = [Replicate() for _ in mesh.dim_names]
        shard_dims = {pl.dim for pl in placements if isinstance(pl, Shard)}
        for d in range(p._data.ndim):
            if d not in shard_dims and p._data.shape[d] % size == 0:
                placements[ax_idx] = Shard(d)
                break
        p._dist_attr = TensorDistAttr(mesh, placements)
    return model


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, **kwargs):
    """Parity: paddle.distributed.sharding.group_sharded_parallel.

    level: "os" (stage1) | "os_g" (stage2) | "p_g_os" (stage3).
    Returns (model, optimizer, scaler) with sharding marks applied; the
    actual partitioning happens when ShardedTrainStep places state on the
    mesh (stage1/2 -> shard_opt_states, stage3 -> param placements).
    """
    from .auto_parallel import get_mesh

    mesh = get_mesh()
    if mesh is None:
        from .fleet import get_fleet_mesh

        mesh = get_fleet_mesh()
    if mesh is None:
        raise RuntimeError("call fleet.init or set_mesh before group_sharded_parallel")
    if level == "p_g_os":
        shard_model_parameters(model, mesh)
    optimizer._group_sharded_level = level
    return model, optimizer, scaler
