"""ShardedTrainStep: the hybrid-parallel compiled train step.

This is where the reference's whole distributed-runtime stack (EagerReducer
bucketed allreduce `reducer.h:88`, sharding-stage optimizers
`dygraph_sharding_optimizer.py:54`, hybrid grad clip
`hybrid_parallel_optimizer.py:275`, reshard insertion) collapses into one
TPU-native mechanism: parameters/optimizer slots/batch are placed on the
hybrid mesh with NamedShardings, the (forward, loss, backward, update)
program is jit-compiled once, and GSPMD emits every collective —
dp gradient psum where grads are partial over "dp", reduce-scatter/
all-gather where states are sharded over "sharding" (ZeRO), TP collectives
where mp placements require them — scheduled and fused by XLA over ICI.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..core.tensor import Parameter, Tensor
from ..jit import TrainStep, _step_update_tail, _unwrap_tensors
from .auto_parallel import (
    ProcessMesh,
    Replicate,
    Shard,
    placements_to_spec,
)

P = PartitionSpec


def _param_sharding(mesh: ProcessMesh, p) -> NamedSharding:
    if getattr(p, "_dist_attr", None) is not None:
        return NamedSharding(
            mesh.jax_mesh,
            placements_to_spec(p._dist_attr.process_mesh, p._dist_attr.placements),
        )
    return NamedSharding(mesh.jax_mesh, P())


def _batch_spec(mesh: ProcessMesh, arr) -> NamedSharding:
    """Shard batch dim 0 over every data-ish axis present (dp, sharding,
    sep). With an ENGAGED ring-attention plan (docs/ATTENTION.md) sep
    stops being a batch axis — the batch routes through
    ``_place_batch_ring`` / ``_ring_batch_sharding`` instead and never
    reaches this function."""
    axes = [a for a in ("dp", "sharding", "sep")
            if a in mesh.dim_names and mesh.get_dim_size(a) > 1]
    if not axes or arr.ndim == 0:
        return NamedSharding(mesh.jax_mesh, P())
    total = int(np.prod([mesh.get_dim_size(a) for a in axes]))
    if arr.shape[0] % total != 0:
        return NamedSharding(mesh.jax_mesh, P())
    return NamedSharding(mesh.jax_mesh, P(tuple(axes)))


class ShardedTrainStep(TrainStep):
    """TrainStep over a hybrid ProcessMesh.

    Placement protocol:
    - params with `_dist_attr` (TP layers, ZeRO-3 marks) -> their placements;
      others replicated.
    - optimizer slots follow their parameter (same shape) or replicate
      (scalars); with `shard_opt_states=True` (ZeRO-1/2) param-shaped slots
      are additionally sharded over the "sharding" axis.
    - batch tensors shard dim 0 over dp×sharding×sep.
    """

    def __init__(self, model, train_fn, optimizer, mesh: ProcessMesh,
                 scaler=None, shard_opt_states=False, shard_vocab_head=None,
                 sharding_stage=None):
        super().__init__(model, train_fn, optimizer, scaler)
        self.mesh = mesh
        self.shard_opt_states = shard_opt_states
        # ZeRO stage (docs/ZERO.md): explicit arg wins, else the
        # group_sharded_parallel level mark on the optimizer. Stage >= 2
        # on a pure-data mesh engages the zero execution mode at build
        # (_ensure_zero_plan): reduce-scattered grads, dp-sharded slots
        # and update, just-in-time param gathers.
        self.sharding_stage = sharding_stage
        self._zero_plan = None
        self._zero_plan_ready = False
        # vocab-sharded LM head ("last-stage-sharded pipeline output"):
        # an axis name places the tied head's vocab dim over that tp axis
        # via model.shard_lm_head, routing the loss through the
        # scalars-per-token sharded CE (models/gpt.py compute_loss). None
        # defers to PTPU_SHARDED_HEAD=<axis|1> (1 -> "mp"); default off so
        # existing mp meshes keep their lowered programs bit-stable.
        if shard_vocab_head is None:
            import os

            env = os.environ.get("PTPU_SHARDED_HEAD", "")
            shard_vocab_head = ("mp" if env == "1"
                                else env if env not in ("", "0") else None)
        self.shard_vocab_head = shard_vocab_head
        self._placed = False
        # dp-grad reduce plan (distributed/collectives): resolved at
        # first trace (knobs are build-time, never per call) — None
        # keeps the pre-PR GSPMD grad psum byte-for-byte
        self._reduce_plan = None
        self._reduce_plan_ready = False
        # ring-attention plan (collectives/ring_attention,
        # docs/ATTENTION.md): when it engages, sep stops being a batch
        # axis — the batch's SEQ dim shards over it (zigzag layout) and
        # attention runs as a kv ring inside the manual region. None
        # keeps sep a plain batch axis, byte-for-byte (PTPU_RING_ATTN=0).
        self._ring_plan = None
        self._ring_plan_ready = False
        self._ring_last_active = False
        # composed hybrid plan (collectives/compose, docs/COMMS.md
        # lattice): when mp and/or pp are live and the model carries a
        # composable flagship decoder, ONE fully-manual region over
        # every live axis composes tp seams + bucketed/quantized grad
        # reduce + ZeRO + the explicit pipeline schedule. None keeps the
        # pre-PR GSPMD program byte-for-byte.
        self._composed_plan = None
        self._composed_plan_ready = False

    # -- placement ---------------------------------------------------------
    def _place_model(self):
        ax = self.shard_vocab_head
        if (ax and ax in self.mesh.dim_names
                and self.mesh.get_dim_size(ax) > 1
                and hasattr(self.model, "shard_lm_head")):
            self.model.shard_lm_head(self.mesh, axis=ax)
        entries = self.model.state_dict()
        for name, t in entries.items():
            sh = _param_sharding(self.mesh, t)
            t._data = jax.device_put(t._data, sh)
        self._placed = True

    def _slot_sharding(self, pname, p_sharding, slot_arr, param_shape):
        plan = self._zero_plan if self._zero_plan_ready else None
        if plan is not None:
            zp = plan.by_name.get(pname)
            if (zp is not None and zp.kind == "flat"
                    and tuple(slot_arr.shape) == (zp.padded,)):
                # zero flat layout: the padded flat slot shards evenly
                # over the shard axis — each rank stores 1/degree
                return NamedSharding(self.mesh.jax_mesh,
                                     P(plan.shard_axis))
        if tuple(slot_arr.shape) == tuple(param_shape):
            if self.shard_opt_states:
                spec = list(p_sharding.spec) + [None] * (
                    len(param_shape) - len(p_sharding.spec)
                )
                taken = {a for e in spec if e for a in (e if isinstance(e, tuple) else (e,))}
                if (
                    "sharding" in self.mesh.dim_names
                    and self.mesh.get_dim_size("sharding") > 1
                    and "sharding" not in taken
                    and len(param_shape) > 0
                ):
                    # the ONE shared dim resolver (compose.stage1_slot_dim)
                    # so the composed region's slot specs match this
                    # storage layout exactly (docs/ZERO.md stage 1)
                    from .collectives.compose import stage1_slot_dim

                    size = self.mesh.get_dim_size("sharding")
                    d = stage1_slot_dim(param_shape, size)
                    if d is not None:
                        cur = spec[d]
                        spec[d] = (
                            ("sharding",) if cur is None
                            else (tuple(cur) if isinstance(cur, tuple) else (cur,)) + ("sharding",)
                        )
                        if not isinstance(spec[d], tuple) or len(spec[d]) == 1:
                            spec[d] = spec[d][0] if isinstance(spec[d], tuple) else spec[d]
                return NamedSharding(self.mesh.jax_mesh, P(*spec))
            return p_sharding
        return NamedSharding(self.mesh.jax_mesh, P())

    def _place_opt_state(self, params):
        entries = self.model.state_dict()
        for name, slots in self._opt_state.items():
            p = entries[name]
            psh = _param_sharding(self.mesh, p)
            for sname, arr in slots.items():
                slots[sname] = jax.device_put(
                    arr, self._slot_sharding(name, psh, arr, p._data.shape)
                )

    def _place_batch(self, raw_batch):
        ring, ring_seq = self._ring_batch_info(raw_batch)
        self._ring_last_active = ring is not None
        if ring is not None:
            return self._place_batch_ring(raw_batch, ring, ring_seq)
        placed = []
        for arr in raw_batch:
            if isinstance(arr, jax.ShapeDtypeStruct):
                # planner path (aot_compile over avals): device_put would
                # reject an abstract value — carry the same sharding a
                # real batch would get so the lowered program matches
                placed.append(jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype,
                    sharding=_batch_spec(self.mesh, arr)))
            elif hasattr(arr, "ndim") and arr.ndim >= 1:
                placed.append(jax.device_put(arr, _batch_spec(self.mesh, arr)))
            else:
                placed.append(arr)
        return tuple(placed)

    def _place_batch_ring(self, raw_batch, plan, seq):
        """Ring placement (docs/ATTENTION.md): seq-dim arrays are
        zigzag-permuted (causal load balance — each rank holds chunk r
        and chunk 2n-1-r) and shard dim 1 over ``sep``; dim 0 shards
        over the remaining data axes only. Loss/grads are permutation-
        invariant (per-token CE over the same token set), so nothing
        un-permutes on the way out."""
        from .collectives import ring_attention as _ring

        plan.set_active_seq(seq)
        perm = jnp.asarray(_ring.zigzag_perm(seq, plan.sep_degree))
        placed = []
        for arr in raw_batch:
            if not hasattr(arr, "ndim") or arr.ndim == 0:
                placed.append(arr)
                continue
            sh = self._ring_batch_sharding(plan, arr, seq)
            if isinstance(arr, jax.ShapeDtypeStruct):
                placed.append(jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype, sharding=sh))
                continue
            if arr.ndim >= 2 and arr.shape[1] == seq:
                arr = jnp.take(arr, perm, axis=1)
            placed.append(jax.device_put(arr, sh))
        return tuple(placed)

    def _ring_batch_sharding(self, plan, arr, seq):
        data = plan.data_axes
        total = int(np.prod([self.mesh.get_dim_size(a) for a in data])) \
            if data else 1
        dim0 = (tuple(data) if data and arr.shape[0] % total == 0
                else None)
        if arr.ndim >= 2 and arr.shape[1] == seq:
            return NamedSharding(self.mesh.jax_mesh, P(dim0, plan.axis))
        return NamedSharding(self.mesh.jax_mesh,
                             P(dim0) if dim0 else P())

    def _ring_batch_info(self, raw_batch):
        """(plan, seq) when the resolved ring plan engages for this
        batch's shapes, else (None, None). Shared by placement and the
        in-step region so the two can never disagree: every ndim>=2
        leaf must carry the SAME dim-1 length and it must pass the
        plan's seq gate (zigzag divisibility + kernel tiling)."""
        plan = self._ensure_ring_plan()
        if plan is None:
            return None, None
        seqs = [int(a.shape[1]) for a in raw_batch
                if hasattr(a, "ndim") and a.ndim >= 2]
        if not seqs:
            return None, None
        seq = seqs[0]
        if any(s != seq for s in seqs) or not plan.seq_ok(seq):
            return None, None
        return plan, seq

    def _prepare_batch(self, raw_batch):
        """memory_stats hook: mirror __call__'s placement so the lowered
        program matches the one real steps run (sharded batch, placed
        model/opt state)."""
        if not self._placed:
            self._place_model()
        if self._opt_state is None:
            entries = self.model.state_dict()
            params = {n: entries[n]._data for n in self._param_names}
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        return self._place_batch(raw_batch)

    # -- ZeRO execution mode (distributed/collectives/zero, docs/ZERO.md) --
    def _zero_deferred(self):
        """{param_name: stacked-attr} for StackedDecoder ``[L, ...]``
        slabs — the params whose stage-3 gathers defer into the scan
        body (models/gpt.py consults ``zero.active_jit_gathers``)."""
        out = {}
        try:
            from ..models.gpt import _BLOCK_PARAM_FIELDS, StackedDecoder
        except Exception:  # pragma: no cover - models optional
            return out
        attrs = [a for a, _ in _BLOCK_PARAM_FIELDS]
        for prefix, layer in self.model.named_sublayers(include_self=True):
            if isinstance(layer, StackedDecoder):
                for attr in attrs:
                    out[(prefix + "." if prefix else "") + attr] = attr
        return out

    def _ensure_zero_plan(self):
        """Resolve (once, at build) whether this step runs the ZeRO
        execution mode. None falls through to the PR 6 reduce plan /
        GSPMD placement-hint path — which is also what
        ``PTPU_QUANT_COLLECTIVES=0`` (pre-PR bytes) and
        ``PTPU_ZERO_MODE=0`` force."""
        if self._zero_plan_ready:
            return self._zero_plan
        self._zero_plan_ready = True
        self._zero_plan = None
        from ..utils.flags import get_flags
        from .collectives import compose as _compose
        from .collectives import zero as _zero

        Reason = _compose.Reason

        def _decline(reason):
            _compose.note_plan_engagement("zero", reason)
            return None

        stage = _zero.resolve_stage(self.optimizer, self.sharding_stage)
        if stage < 2:
            return _decline(Reason.STAGE_LT_2)
        if get_flags("check_nan_inf")["check_nan_inf"]:
            # checkify cannot instrument through the manual region
            return _decline(Reason.CHECKIFY)
        entries = self.model.state_dict()
        named = []
        for n, t in entries.items():
            if not isinstance(t, Parameter):
                continue
            if t.trainable:
                named.append((n, t))
                continue
            # a FROZEN param with a data-axis Shard placement would ride
            # the zero step as a replicated "buffer" — gathered every
            # step and written back full, silently dropping its shard
            # residency (and pmean'd). The GSPMD hint path handles
            # frozen shards correctly, so decline the whole mode
            # (partial-finetune stage-3 keeps the pre-PR program).
            da = getattr(t, "_dist_attr", None)
            if da is not None and any(
                    isinstance(pl, Shard)
                    and da.process_mesh.get_dim_size(ax) > 1
                    and ax in ("dp", "sharding")
                    for ax, pl in zip(da.process_mesh.dim_names,
                                      da.placements)):
                return _decline(Reason.FROZEN_SHARD)
        reasons = []
        self._zero_plan = _zero.build_zero_plan(
            named, self.mesh, stage, optimizer=self.optimizer,
            grad_clip=self.optimizer._grad_clip,
            deferred=self._zero_deferred(), reason_out=reasons)
        _compose.note_plan_engagement(
            "zero", Reason.ENGAGED if self._zero_plan is not None
            else (reasons[0] if reasons else Reason.UNSPECIFIED))
        return self._zero_plan

    def zero_plan(self):
        """The resolved ZeroPlan (None = GSPMD / PR 6 path) — the bench
        "zero" block embeds its zero_summary()."""
        return self._zero_plan if self._zero_plan_ready else None

    def _build(self):
        cplan = self._ensure_composed_plan()
        if cplan is not None:
            # the composed plan owns the whole step: comms accounting
            # rides its GradReducePlan duck-type, and its inner zero
            # plan (possibly None) drives the slot-layout hooks
            self._reduce_plan = cplan
            self._reduce_plan_ready = True
            self._zero_plan = cplan.zero
            self._zero_plan_ready = True
            return self._build_composed(cplan)
        plan = self._ensure_zero_plan()
        if plan is None:
            return super()._build()
        # the zero plan owns the whole step: the PR 6 reduce plan must
        # not also engage (one manual region), and the comms accounting
        # rides the same seam (ZeroPlan duck-types GradReducePlan)
        self._reduce_plan = plan
        self._reduce_plan_ready = True
        self._build_zero(plan)

    # -- composed hybrid mode (distributed/collectives/compose) ------------
    def _ensure_composed_plan(self):
        """Resolve (once, at build) whether this step runs the composed
        hybrid mode — see collectives/compose.py's lattice. None falls
        through to the zero / reduce / ring plans (pure-data meshes) or
        the pre-PR GSPMD program (declined hybrids)."""
        if self._composed_plan_ready:
            return self._composed_plan
        self._composed_plan_ready = True
        self._composed_plan = None
        from .collectives import compose as _compose

        plan, reason = _compose.build_composed_plan(
            self.model, self.optimizer, self.mesh,
            sharding_stage=self.sharding_stage,
            shard_vocab_head=self.shard_vocab_head,
            grad_clip=self.optimizer._grad_clip,
            shard_opt_states=self.shard_opt_states)
        _compose.note_plan_engagement("composed", reason)
        self._composed_plan = plan
        return plan

    def composed_plan(self):
        """The resolved ComposedPlan (None = per-plan/GSPMD path) — the
        bench "comms" block embeds its summary()."""
        return self._composed_plan if self._composed_plan_ready else None

    def _build_composed(self, plan):
        """Compile the composed step: ONE fully-manual shard_map region
        over every live axis containing (gather/stage-slice params ->
        forward with in-region tp seams and the inline pipeline ring ->
        loss -> backward -> bucketed/quantized + zero grad reduce ->
        clip/guard -> sharded update). Mirrors _build_zero's step
        semantics operation for operation (docs/COMMS.md lattice,
        docs/PIPELINE.md schedule contract)."""
        import jax as _jax
        from jax import shard_map

        from .. import framework
        from ..jit import _wrap_arrays
        from ..utils.flags import get_flags as _gf
        from . import collectives
        from .collectives import compose as _compose
        from .collectives import zero as _zero
        from .. import telemetry as _telemetry

        model, train_fn, opt = self.model, self.train_fn, self.optimizer
        _telemetry.record_compile(
            self._compile_label(),
            ("build", bool(_gf("check_nan_inf")["check_nan_inf"]),
             "composed", plan.tp, plan.pp,
             plan.zero.stage if plan.zero else 0))
        entries = model.state_dict()
        self._param_names = [
            n for n, t in entries.items()
            if isinstance(t, Parameter) and t.trainable
        ]
        self._buffer_names = [n for n in entries
                              if n not in self._param_names]
        buffer_names = tuple(self._buffer_names)
        clip = opt._grad_clip
        reg = opt.regularization
        axes = plan.axes
        data_axes = plan.data_axes
        data_total = int(np.prod([self.mesh.get_dim_size(a)
                                  for a in data_axes])) if data_axes else 1
        zplan = plan.zero
        deferred_info = {}
        if zplan is not None:
            deferred_info = {
                p.deferred_attr: (zplan.shard_axis, p.shard_dim,
                                  zplan.shard_degree,
                                  zplan.gather_quantized)
                for p in zplan.params if p.deferred_attr}

        def make_loss_of(buffers, key_arr, batch):
            def loss_of(params):
                state = {}
                for n, p in params.items():
                    zp = zplan.by_name.get(n) if zplan is not None else None
                    if (zp is not None and zp.kind == "dim"
                            and zp.deferred_attr is None):
                        p = _zero.gather_shard(
                            p, zplan.shard_axis, zp.shard_dim,
                            degree=zplan.shard_degree,
                            quantized=zplan.gather_quantized)
                    state[n] = p
                state.update(buffers)
                with model._swap_state(state) as mutated:
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        loss_t = train_fn(*_wrap_arrays(batch))
                new_buffers = {n: mutated[n] for n in buffer_names}
                return loss_t._data, new_buffers

            return loss_of

        def per_shard(params, buffers, opt_state, lr_, guard_, key_,
                      rng_ids, z_ids, s1_ids, tp_ids, pp_ids, *batch):
            # ordinals ride in as sharded iotas (lax.axis_index lowers
            # to PartitionId, rejected here); the RNG stream folds the
            # DATA ordinal only — mp/pp ranks replicate the same draws
            key = _jax.random.fold_in(key_, rng_ids[0])
            ctx = _compose.ComposedContext(
                plan, tp_ordinal=tp_ids[0], stage_ordinal=pp_ids[0])
            loss_of = make_loss_of(buffers, key, batch)
            with _compose.composed_scope(ctx), \
                    _zero.jit_gather_scope(deferred_info):
                (loss, new_buffers), grads = _jax.value_and_grad(
                    loss_of, has_aux=True)(params)
            if plan.tp_seams and (ctx.seams is None
                                  or ctx.seams.calls == 0):
                raise RuntimeError(
                    "composed plan engaged tp seams but the model's "
                    "trace never routed a matmul through them "
                    "(models/gpt.py _block_pure) — the step would "
                    "compute on weight SHARDS as if they were full. "
                    "Use a flagship decoder stack or disable with "
                    "PTPU_COMPOSED=0 (docs/COMMS.md).")
            if data_axes:
                loss = _jax.lax.pmean(loss, data_axes)
                new_buffers = {
                    n: (_jax.lax.pmean(v, data_axes)
                        if jnp.issubdtype(v.dtype, jnp.inexact) else v)
                    for n, v in new_buffers.items()}
            zero_ord = z_ids[0]
            grads = _compose.reduce_grads(grads, plan, zero_ord)
            upd_params = _compose.update_view(params, plan, zero_ord)
            # stage-1 slot sharding (shard_opt_states): gather the
            # 1/degree slot shards to their full update view exactly;
            # the update runs the replicated math bit-for-bit and the
            # result slices back to the shard below — resident slot
            # storage never leaves its dp-sharded layout
            opt_state = _compose.stage1_gather_slots(opt_state, params,
                                                     plan)
            loss, new_upd, new_buffers, new_opt_state, health = \
                _step_update_tail(
                    opt, clip, reg, upd_params, grads, loss, new_buffers,
                    buffers, opt_state, lr_, guard_,
                    gsumsq_fn=lambda g: _compose.global_grad_sumsq(
                        g, plan))
            new_opt_state = _compose.stage1_slice_slots(
                new_opt_state, params, plan, s1_ids[0])
            new_params = _compose.params_out(new_upd, plan)
            return loss, new_params, new_buffers, new_opt_state, health

        def step(params, buffers, opt_state, lr, guard, key_arr, batch):
            def leaf_spec(arr):
                if (data_axes and hasattr(arr, "ndim") and arr.ndim >= 1
                        and arr.shape[0] % data_total == 0):
                    return P(data_axes)
                return P()

            batch_specs = tuple(leaf_spec(a) for a in batch)
            pspecs = {n: plan.param_specs.get(n, P()) for n in params}
            bspecs = {n: P() for n in buffers}
            nbspecs = {n: P() for n in buffer_names}

            def slot_spec(n, leaf):
                zp = zplan.by_name.get(n) if zplan is not None else None
                if (zp is not None and zp.kind == "flat"
                        and tuple(leaf.shape) == (zp.padded,)):
                    return P(zplan.shard_axis)
                # param-shaped slots follow the param's storage spec
                # (pipeline/TP-sharded optimizer state for free); a
                # stage-1 (shard_opt_states) slot additionally carries
                # its "sharding" extension — the dp-sharded layout rides
                # THROUGH the region instead of resharding to replicated
                if tuple(leaf.shape) == tuple(entries[n]._data.shape):
                    base = plan.param_specs.get(n, P())
                    sd = plan.slot_shards.get(n)
                    if sd is not None:
                        return _compose.stage1_slot_spec(base, sd[0])
                    return base
                return P()

            sspecs = {n: {k: slot_spec(n, v) for k, v in slots.items()}
                      for n, slots in opt_state.items()}
            rng_ids = jnp.arange(max(data_total, 1), dtype=jnp.int32)
            rng_spec = P(data_axes) if data_axes else P()
            if zplan is not None:
                z_ids = jnp.arange(zplan.shard_degree, dtype=jnp.int32)
                z_spec = P(zplan.shard_axis)
            else:
                z_ids = jnp.zeros((1,), jnp.int32)
                z_spec = P()
            if plan.slot_shards:
                s1_deg = next(iter(plan.slot_shards.values()))[1]
                s1_ids = jnp.arange(s1_deg, dtype=jnp.int32)
                s1_spec = P("sharding")
            else:
                s1_ids = jnp.zeros((1,), jnp.int32)
                s1_spec = P()
            if plan.tp_axis:
                tp_ids = jnp.arange(plan.tp, dtype=jnp.int32)
                tp_spec = P(plan.tp_axis)
            else:
                tp_ids = jnp.zeros((1,), jnp.int32)
                tp_spec = P()
            if plan.pp_axis:
                pp_ids = jnp.arange(plan.pp, dtype=jnp.int32)
                pp_spec = P(plan.pp_axis)
            else:
                pp_ids = jnp.zeros((1,), jnp.int32)
                pp_spec = P()
            with collectives.manual_grad_region():
                return shard_map(
                    per_shard, mesh=self.mesh.jax_mesh,
                    in_specs=(pspecs, bspecs, sspecs, P(), P(), P(),
                              rng_spec, z_spec, s1_spec, tp_spec, pp_spec)
                    + batch_specs,
                    out_specs=(P(), pspecs, nbspecs, sspecs, P()),
                    check_vma=False, axis_names=set(axes),
                )(params, buffers, opt_state, lr, guard, key_arr,
                  rng_ids, z_ids, s1_ids, tp_ids, pp_ids, *batch)

        self._execs = {}
        self._checkified = False
        self._compiled = jax.jit(step, donate_argnums=(0, 2))

    def _build_zero(self, plan):
        """Compile the ZeRO step: one fully-manual shard_map region over
        the data axes containing (gather params -> forward -> loss ->
        backward -> reduce-scatter grads -> clip/guard -> SHARDED
        optimizer update). Mirrors TrainStep._build's step semantics
        operation for operation — the chaos seam, regularizer, global-
        norm clip, StepHealth bundle, and guard skip-select all behave
        identically, just on 1/degree shards (docs/ZERO.md numerics
        contract)."""
        import jax as _jax
        from jax import shard_map

        from .. import framework
        from ..jit import _wrap_arrays
        from ..utils.flags import get_flags as _gf
        from . import collectives
        from .collectives import zero as _zero
        from .. import telemetry as _telemetry

        model, train_fn, opt = self.model, self.train_fn, self.optimizer
        _telemetry.record_compile(
            self._compile_label(),
            ("build", bool(_gf("check_nan_inf")["check_nan_inf"]), "zero",
             plan.stage))
        entries = model.state_dict()
        self._param_names = [
            n for n, t in entries.items()
            if isinstance(t, Parameter) and t.trainable
        ]
        self._buffer_names = [n for n in entries
                              if n not in self._param_names]
        buffer_names = tuple(self._buffer_names)
        clip = opt._grad_clip
        reg = opt.regularization
        axes = plan.axes
        total = plan.nranks
        deferred_info = {
            p.deferred_attr: (plan.shard_axis, p.shard_dim,
                              plan.shard_degree, plan.gather_quantized)
            for p in plan.params if p.deferred_attr}

        def make_loss_of(buffers, key_arr, batch):
            def loss_of(params):
                # stage-3 just-in-time gathers: non-deferred dim shards
                # gather here (AD of the gather IS the grad reduce-
                # scatter); deferred slabs stay shards — the scan body
                # gathers them per layer via the jit_gather scope
                state = {}
                for n, p in params.items():
                    zp = plan.by_name[n]
                    if zp.kind == "dim" and zp.deferred_attr is None:
                        p = _zero.gather_shard(
                            p, plan.shard_axis, zp.shard_dim,
                            degree=plan.shard_degree,
                            quantized=plan.gather_quantized)
                    state[n] = p
                state.update(buffers)
                with model._swap_state(state) as mutated:
                    with framework.no_grad(), framework.rng_key_scope(key_arr):
                        loss_t = train_fn(*_wrap_arrays(batch))
                new_buffers = {n: mutated[n] for n in buffer_names}
                return loss_t._data, new_buffers

            return loss_of

        def per_shard(params, buffers, opt_state, lr_, guard_, key_,
                      rng_ids, shard_ids, *batch):
            # per-shard RNG stream + ordinals ride in as sharded iotas
            # (lax.axis_index lowers to PartitionId, rejected here)
            key = _jax.random.fold_in(key_, rng_ids[0])
            ordinal = shard_ids[0]
            loss_of = make_loss_of(buffers, key, batch)
            with _zero.jit_gather_scope(deferred_info):
                (loss, new_buffers), grads = _jax.value_and_grad(
                    loss_of, has_aux=True)(params)
            loss = _jax.lax.pmean(loss, axes)
            new_buffers = {
                n: (_jax.lax.pmean(v, axes)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v)
                for n, v in new_buffers.items()}
            grads = {n: _zero.reduce_grad(g, plan.by_name[n], plan,
                                          ordinal, mean=True)
                     for n, g in grads.items()}
            upd_params = _zero.update_view(params, plan, ordinal)
            # the ONE step tail (chaos inject -> reg -> health -> clip
            # -> update -> guard keep-select, jit._step_update_tail):
            # shared with the base TrainStep so PR 5 guard semantics
            # cannot drift between zero and non-zero steps — here it
            # runs on the shard views, with the sumsq psum'd over the
            # shard axis (ClipGradByNorm declined the plan at build)
            loss, new_upd, new_buffers, new_opt_state, health = \
                _step_update_tail(
                    opt, clip, reg, upd_params, grads, loss, new_buffers,
                    buffers, opt_state, lr_, guard_,
                    gsumsq_fn=lambda g: _zero.global_grad_sumsq(g, plan))
            new_params = _zero.params_out(new_upd, plan)
            return loss, new_params, new_buffers, new_opt_state, health

        def step(params, buffers, opt_state, lr, guard, key_arr, batch):
            def leaf_spec(arr):
                if (hasattr(arr, "ndim") and arr.ndim >= 1
                        and arr.shape[0] % total == 0):
                    return P(axes)
                return P()

            batch_specs = tuple(leaf_spec(a) for a in batch)
            pspecs = {n: (plan.by_name[n].spec
                          if plan.by_name[n].kind == "dim" else P())
                      for n in params}
            bspecs = {n: P() for n in buffers}
            nbspecs = {n: P() for n in buffer_names}

            def slot_spec(n, leaf):
                zp = plan.by_name[n]
                if (zp.kind == "flat"
                        and tuple(leaf.shape) == (zp.padded,)):
                    return P(plan.shard_axis)
                if zp.kind == "dim" and tuple(leaf.shape) == zp.shape:
                    return zp.spec
                return P()

            sspecs = {n: {k: slot_spec(n, v) for k, v in slots.items()}
                      for n, slots in opt_state.items()}
            rng_ids = jnp.arange(total, dtype=jnp.int32)
            shard_ids = jnp.arange(plan.shard_degree, dtype=jnp.int32)
            with collectives.manual_grad_region():
                return shard_map(
                    per_shard, mesh=self.mesh.jax_mesh,
                    in_specs=(pspecs, bspecs, sspecs, P(), P(), P(),
                              P(axes), P(plan.shard_axis)) + batch_specs,
                    out_specs=(P(), pspecs, nbspecs, sspecs, P()),
                    check_vma=False, axis_names=set(axes),
                )(params, buffers, opt_state, lr, guard, key_arr,
                  rng_ids, shard_ids, *batch)

        self._execs = {}
        self._checkified = False
        self._compiled = jax.jit(step, donate_argnums=(0, 2))

    # -- zero slot layout --------------------------------------------------
    def _functional_state(self, params):
        """Fresh functional slots in the layout the step runs: under an
        engaged ZeroPlan, flat-kind params get flat ``[padded]`` slots
        (Optimizer.functional_state shard_spec) so the dp-sharded update
        owns a contiguous chunk per rank."""
        plan = self._ensure_zero_plan()
        spec = None
        if plan is not None:
            spec = {p.name: p.padded for p in plan.params
                    if p.kind == "flat"}
        return self.optimizer.functional_state(params,
                                               shard_spec=spec or None)

    def _adapt_restored_slot(self, arr, tgt, pname, pshape):
        """Flat-layout conversions for restored slots (docs/ZERO.md
        checkpoint contract), on top of the base rules: when the target
        is a flat ``[padded]`` dp-sharded slot, accept a same-length
        flat slot, a param-shaped slot (flatten + zero-pad — a non-zero
        checkpoint restoring into a zero run), or ANOTHER degree's flat
        slot (un-pad to numel, re-pad — the elastic-restart case where
        the padded length changed with the shard degree)."""
        plan = self._zero_plan if self._zero_plan_ready else None
        zp = plan.by_name.get(pname) if plan is not None else None
        if (zp is not None and zp.kind == "flat"
                and tuple(tgt.shape) == (zp.padded,)):
            if tuple(arr.shape) == (zp.padded,):
                return arr
            flat = arr.reshape(-1)
            if flat.size == zp.numel or (arr.ndim == 1
                                         and flat.size >= zp.numel):
                flat = flat[:zp.numel]
                return jnp.pad(flat, (0, zp.padded - zp.numel))
            return None
        return super()._adapt_restored_slot(arr, tgt, pname, pshape)

    # -- quantized/bucketed dp-grad reduce (distributed/collectives) -------
    def _ensure_reduce_plan(self):
        """Resolve (once) whether this step owns its dp grad reduce.

        Falls back to the inherited GSPMD program (plan None) whenever
        the restructure is unsafe or worthless on this runtime: master
        knob off, checkify debug mode, a live mesh axis outside
        {dp, sharding, mp} (pipeline/sep/ep kernels open their own
        manual regions, which cannot nest inside ours on this XLA), a
        param placement on a data axis (ZeRO-3), a vocab-sharded head
        (same nesting limit), or no gradient big enough to quantize."""
        if self._reduce_plan_ready:
            return self._reduce_plan
        self._reduce_plan_ready = True
        self._reduce_plan = None
        from ..utils.flags import get_flags
        from . import collectives
        from .collectives import compose as _compose

        Reason = _compose.Reason

        def _decline(reason):
            _compose.note_plan_engagement("grad_reduce", reason)
            return None

        if not collectives.quant_collectives_enabled():
            return _decline(Reason.MASTER_OFF)
        if get_flags("check_nan_inf")["check_nan_inf"]:
            return _decline(Reason.CHECKIFY)
        mp_live = ("mp" in self.mesh.dim_names
                   and self.mesh.get_dim_size("mp") > 1)
        if self.shard_vocab_head and mp_live:
            # the vocab-sharded CE opens its own mp shard_map island
            return _decline(Reason.VOCAB_SHARDED_HEAD)
        if collectives.tp_seam_mode() == "fused" and mp_live:
            # explicit seam forcing: the seam islands win the one manual
            # region this XLA allows (docs/COMMS.md precedence)
            return _decline(Reason.SEAM_FORCED)
        entries = self.model.state_dict()
        taken = set()
        for n in self._param_names:
            da = getattr(entries[n], "_dist_attr", None)
            if da is None:
                continue
            for ax_name, pl in zip(da.process_mesh.dim_names, da.placements):
                if isinstance(pl, Shard):
                    taken.add(ax_name)
        if taken & {"dp", "sharding"}:
            # ZeRO-3: a param placement on a DATA axis means the forward
            # must all-gather params inside the region, and gather with
            # manual subgroups is exactly the lowering this XLA rejects
            # (docs/COMMS.md runtime limits) — those placements stay
            # with GSPMD end to end, on every data axis
            return _decline(Reason.ZERO3_PLACEMENT)
        named = [(n, tuple(entries[n]._data.shape),
                  entries[n]._data.dtype) for n in self._param_names]
        reasons = []
        self._reduce_plan = collectives.build_grad_reduce_plan(
            named, self.mesh, reason_out=reasons)
        _compose.note_plan_engagement(
            "grad_reduce", Reason.ENGAGED if self._reduce_plan is not None
            else (reasons[0] if reasons else Reason.UNSPECIFIED))
        return self._reduce_plan

    def comms_plan(self):
        """The active grad-reduce plan (None = pre-PR GSPMD path) — the
        bench/dryrun "comms" block embeds its summary(). An engaged ring
        plan owns its own composed reduce (axes = data + sep)."""
        if self._ring_last_active and self._ring_plan is not None:
            return self._ring_plan.reduce
        return self._reduce_plan if self._reduce_plan_ready else None

    # -- ring attention over sep (collectives/ring_attention) --------------
    def _ensure_ring_plan(self):
        """Resolve (once, at build) whether this step runs context
        parallelism as ring attention over ``sep`` (docs/ATTENTION.md).
        Declines — keeping sep a plain batch axis and the program
        byte-for-byte pre-PR — on: the PTPU_RING_ATTN=0 escape hatch,
        checkify debug mode, ZeRO stage >= 2 (the zero mode owns the
        manual region, and itself declines sep-live meshes), a vocab-
        sharded head (its shard_map island cannot nest in ours), any
        live axis outside {dp, sharding, sep}, and models without a
        ring-eligible decoder stack."""
        if self._ring_plan_ready:
            return self._ring_plan
        self._ring_plan_ready = True
        self._ring_plan = None
        from ..utils.flags import get_flags
        from .collectives import compose as _compose
        from .collectives import ring_attention as _ring
        from .collectives import zero as _zero

        Reason = _compose.Reason

        def _decline(reason):
            _compose.note_plan_engagement("ring_attn", reason)
            return None

        if ("sep" not in self.mesh.dim_names
                or self.mesh.get_dim_size("sep") < 2):
            return None  # not a sep mesh at all: nothing to resolve
        if not _ring.ring_attn_enabled():
            from . import collectives

            return _decline(Reason.MASTER_OFF
                            if not collectives.quant_collectives_enabled()
                            else Reason.RING_OFF)
        if get_flags("check_nan_inf")["check_nan_inf"]:
            return _decline(Reason.CHECKIFY)
        if _zero.resolve_stage(self.optimizer, self.sharding_stage) >= 2:
            return _decline(Reason.ZERO_REQUESTED)
        if (self.shard_vocab_head
                and self.shard_vocab_head in self.mesh.dim_names
                and self.mesh.get_dim_size(self.shard_vocab_head) > 1):
            return _decline(Reason.VOCAB_SHARDED_HEAD)
        entries = self.model.state_dict()
        if not self._param_names:
            self._param_names = [
                n for n, t in entries.items()
                if isinstance(t, Parameter) and t.trainable]
        named = [(n, tuple(entries[n]._data.shape), entries[n]._data.dtype)
                 for n in self._param_names]
        reasons = []
        self._ring_plan = _ring.build_ring_attn_plan(
            named, self.mesh, self.model, reason_out=reasons)
        _compose.note_plan_engagement(
            "ring_attn", Reason.ENGAGED if self._ring_plan is not None
            else (reasons[0] if reasons else Reason.UNSPECIFIED))
        return self._ring_plan

    def ring_plan(self):
        """The resolved RingAttnPlan (None = sep stays a batch axis) —
        the bench "ring" block embeds its summary()."""
        return self._ring_plan if self._ring_plan_ready else None

    def _ring_value_and_grads(self, plan, seq, make_loss_of, params,
                              buffers, key_arr, batch):
        """The engaged-ring differentiation seam: ONE manual shard_map
        region over (data axes + sep). The residual stream stays
        sep-sharded between layers — only attention communicates, as a
        kv ring (models/gpt.py routes ``_sdpa_pure`` through
        ``ring_attention`` while the scope is active, and rope reads
        zigzag GLOBAL positions from the context). The fused-CE head
        runs on the token shard (no logits or hidden gather); the loss
        pmeans and every grad — partial over sep because each shard
        back-propagated only its local tokens — reduces through the
        plan's composed bucketed/quantized reduce."""
        import jax as _jax
        from jax import shard_map

        from . import collectives
        from .collectives import ring_attention as _ring

        axes = plan.axes
        data_axes = plan.data_axes
        data_total = int(np.prod([self.mesh.get_dim_size(a)
                                  for a in data_axes])) if data_axes else 1

        def leaf_spec(arr):
            if not hasattr(arr, "ndim") or arr.ndim == 0:
                return P()
            dim0 = (tuple(data_axes)
                    if data_axes and arr.shape[0] % data_total == 0
                    else None)
            if arr.ndim >= 2 and arr.shape[1] == seq:
                return P(dim0, plan.axis)
            return P(dim0) if dim0 else P()

        batch_specs = tuple(leaf_spec(a) for a in batch)
        pspecs = {n: P() for n in params}
        bspecs = {n: P() for n in buffers}
        nbspecs = {n: P() for n in self._buffer_names}

        def per_shard(params, buffers, key_arr, shard_id, sep_id, *batch):
            # per-shard RNG: fold the GLOBAL (dp x sep) ordinal into the
            # step key — the PR 6 dp discipline extended with the sep
            # ordinal, so dropout-style draws stay independent across
            # token shards too. Both ordinals ride in as sharded iotas
            # (lax.axis_index lowers to PartitionId, rejected here).
            key = _jax.random.fold_in(key_arr, shard_id[0])
            ctx = _ring.RingContext(plan.axis, plan.sep_degree,
                                    sep_id[0], plan=plan)
            loss_of = make_loss_of(buffers, key, batch)
            with _ring.ring_scope(ctx):
                (loss, new_buffers), grads = _jax.value_and_grad(
                    loss_of, has_aux=True)(params)
            # mean of per-shard token means == the global mean when
            # shards hold equal valid-token counts (the dp caveat,
            # docs/COMMS.md, now also across sep token shards)
            loss = _jax.lax.pmean(loss, axes)
            new_buffers = {
                n: (_jax.lax.pmean(v, axes)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v)
                for n, v in new_buffers.items()}
            grads = collectives.reduce_grads(grads, plan.reduce,
                                             mean=True)
            return loss, new_buffers, grads

        shard_ids = jnp.arange(plan.nranks, dtype=jnp.int32)
        sep_ids = jnp.arange(plan.sep_degree, dtype=jnp.int32)
        plan.calls_traced = 0
        with collectives.manual_grad_region():
            out = shard_map(
                per_shard, mesh=self.mesh.jax_mesh,
                in_specs=(pspecs, bspecs, P(), P(axes), P(plan.axis))
                + batch_specs,
                out_specs=(P(), nbspecs, pspecs),
                check_vma=False, axis_names=set(axes),
            )(params, buffers, key_arr, shard_ids, sep_ids, *batch)
        if plan.calls_traced == 0:
            raise RuntimeError(
                "ring attention plan engaged but the model's trace never "
                "routed attention through the ring seam "
                "(models/gpt.py _sdpa_pure) — the step would silently "
                "compute LOCAL-only attention. Use a flagship decoder "
                "stack or disable with PTPU_RING_ATTN=0 "
                "(docs/ATTENTION.md).")
        loss, new_buffers, grads = out
        return (loss, new_buffers), grads

    def _value_and_grads(self, make_loss_of, params, buffers, key_arr,
                         batch):
        # checkify debug rebuilds (FLAGS_check_nan_inf flipped after the
        # first build) must not reuse an engaged plan: checkify cannot
        # instrument through the manual region
        if getattr(self, "_checkified", False):
            return super()._value_and_grads(make_loss_of, params, buffers,
                                            key_arr, batch)
        ring, ring_seq = self._ring_batch_info(batch)
        if ring is not None:
            return self._ring_value_and_grads(ring, ring_seq,
                                              make_loss_of, params,
                                              buffers, key_arr, batch)
        plan = self._ensure_reduce_plan()
        if plan is None:
            return super()._value_and_grads(make_loss_of, params, buffers,
                                            key_arr, batch)
        import jax as _jax
        from jax import shard_map

        from . import collectives

        axes = plan.axes
        total = int(np.prod([self.mesh.get_dim_size(a) for a in axes]))

        def leaf_spec(arr):
            # mirror _batch_spec: dim 0 over the data axes when it splits
            if (hasattr(arr, "ndim") and arr.ndim >= 1
                    and arr.shape[0] % total == 0):
                return P(axes)
            return P()

        batch_specs = tuple(leaf_spec(a) for a in batch)
        pspecs = {n: P() for n in params}
        bspecs = {n: P() for n in buffers}
        nbspecs = {n: P() for n in self._buffer_names}

        def per_shard(params, buffers, key_arr, shard_id, *batch):
            # per-shard loss over the LOCAL batch rows; grads are the
            # per-rank partials the bucketed/quantized reduce combines.
            # NOTE the dp-mean here averages per-shard means — identical
            # to the global mean when shards hold equal valid-token
            # counts (a masked-loss skew shifts weighting by at most the
            # count imbalance; docs/COMMS.md)
            #
            # per-shard RNG stream: fold the shard ordinal into the step
            # key so dropout masks are independent across data shards
            # (the pre-PR global trace drew one mask per GLOBAL row; the
            # same key on every shard would tile one local mask pattern
            # across the batch). lax.axis_index lowers to PartitionId,
            # which this XLA rejects — the ordinal rides in as a
            # P(axes)-sharded iota instead (the sharded-CE trick).
            key = _jax.random.fold_in(key_arr, shard_id[0])
            loss_of = make_loss_of(buffers, key, batch)
            (loss, new_buffers), grads = _jax.value_and_grad(
                loss_of, has_aux=True)(params)
            loss = _jax.lax.pmean(loss, axes)
            # dp-consistent buffers: a batch-updated float buffer (BN-
            # style running stats) is computed from the LOCAL shard here
            # where the pre-PR program saw the global batch — pmean makes
            # the stored value deterministic and exact for linear
            # running-stat updates (mean of per-shard means). Replicated
            # untouched buffers pass through bitwise for power-of-two
            # shard counts; non-float buffers stay local (docs/COMMS.md).
            new_buffers = {
                n: (_jax.lax.pmean(v, axes)
                    if jnp.issubdtype(v.dtype, jnp.inexact) else v)
                for n, v in new_buffers.items()}
            grads = collectives.reduce_grads(grads, plan, mean=True)
            return loss, new_buffers, grads

        shard_ids = jnp.arange(total, dtype=jnp.int32)
        # a live-but-placement-free mp axis joins the region as a MANUAL
        # axis (params enter replicated; every mp rank runs the same
        # per-shard math redundantly, exactly what GSPMD computed for
        # it). Leaving it AUTO lets sharding propagation reach
        # instructions inside the manual region, which this XLA's
        # partitioner hard-aborts on (IsManualSubgroup CHECK — the
        # pre-existing example-02 crash class). The reduce axes
        # (plan.axes) are unchanged: no mp collective is ever emitted.
        region_axes = set(axes)
        if ("mp" in self.mesh.dim_names
                and self.mesh.get_dim_size("mp") > 1):
            region_axes.add("mp")
        with collectives.manual_grad_region():
            loss, new_buffers, grads = shard_map(
                per_shard, mesh=self.mesh.jax_mesh,
                in_specs=(pspecs, bspecs, P(), P(axes)) + batch_specs,
                out_specs=(P(), nbspecs, pspecs),
                check_vma=False, axis_names=region_axes,
            )(params, buffers, key_arr, shard_ids, *batch)
        return (loss, new_buffers), grads

    # -- step --------------------------------------------------------------
    def _call_impl(self, *batch):
        # the base __call__ owns the per-step instrumentation
        # (train_step_seconds/train_steps_total + the train_step trace
        # span, docs/TELEMETRY.md) — overriding only the impl keeps it
        # in ONE place for exactly the multi-chip runs where step
        # timing matters most
        return self._sharded_call(*batch)

    def _sharded_call(self, *batch):
        if not self._placed:
            self._place_model()
        first_state = self._opt_state is None
        from ..utils.flags import get_flags

        want_check = bool(get_flags("check_nan_inf")["check_nan_inf"])
        if self._compiled is None or want_check != getattr(
                self, "_checkified", False):
            if self._compiled is not None:
                # FLAGS_check_nan_inf flipped since the last build
                # (mirrors TrainStep._call_impl): re-resolve the plans —
                # checkify declines the composed/zero modes and the PR 6
                # reduce plan — and rebuild with/without instrumentation
                self._zero_plan_ready = False
                self._reduce_plan = None
                self._reduce_plan_ready = False
                self._ring_plan = None
                self._ring_plan_ready = False
                self._composed_plan = None
                self._composed_plan_ready = False
            self._build()
        entries = self.model.state_dict()
        params = {n: entries[n]._data for n in self._param_names}
        if first_state:
            self._opt_state = self._init_opt_state(params)
            self._place_opt_state(params)
        raw_batch = self._place_batch(_unwrap_tensors(batch))
        buffers = {n: entries[n]._data for n in self._buffer_names}
        lr = self.optimizer.get_lr()
        guard_arr = self._guard_operand()
        from .. import framework

        key_arr = framework.next_rng_key()
        # no ambient mesh context needed: every input carries an explicit
        # NamedSharding, and constraints inside the program name their mesh.
        out = self._dispatch_compiled(
            params, buffers, self._opt_state, lr, guard_arr, key_arr,
            raw_batch
        )
        if self._checkified:
            # raise BEFORE adopting any output (base-step semantics):
            # params/buffers/opt state stay at their pre-step values
            err, out = out
            err.throw()
        loss, new_params, new_buffers, self._opt_state, health = out
        self._last_health = health
        for n, arr in new_params.items():
            entries[n]._data = arr
        for n, arr in new_buffers.items():
            entries[n]._data = arr
        self.optimizer._step_count += 1
        # comms accounting: one tick per executed step with the plan's
        # static payload split (exact vs int8) — the counters behind the
        # bench "comms" block (docs/COMMS.md)
        from .collectives import (note_grad_reduce, note_ring_attn,
                                  note_zero_step)

        if self._ring_last_active and self._ring_plan is not None:
            # an engaged ring step owns its composed grad reduce (axes =
            # data + sep) and additionally rotates KV around the ring
            note_grad_reduce(self._ring_plan.reduce)
            note_ring_attn(self._ring_plan)
        else:
            note_grad_reduce(self._reduce_plan)
            note_zero_step(self._reduce_plan)
        # quant-compute flops accounting (docs/QUANT.md): per-step tick at
        # the rate the last engaged trace recorded (global batch tokens)
        from ..quant import note_step_tokens

        shape = getattr(raw_batch[0], "shape", ()) if raw_batch else ()
        note_step_tokens(int(shape[0]) * int(shape[1])
                         if len(shape) >= 2 else 0)
        return Tensor(loss)


# ---------------------------------------------------------------------------
# ZeRO / group-sharded marks (parity: group_sharded_parallel,
# dygraph_sharding_optimizer.py:54, group_sharded_stage{2,3}.py)
# ---------------------------------------------------------------------------
def shard_model_parameters(model, mesh: ProcessMesh, axis="sharding"):
    """ZeRO-3: give every parameter a Shard placement over `axis` on its
    first divisible NON-LEADING dim — falling back to dim 0, else
    replicated.

    Non-leading dims are preferred because a multi-dim parameter's
    leading axis is the layer axis for the stacked-decoder ``[L, ...]``
    slabs: a Shard(0) slab cannot defer its gather into the scan body
    (each rank would scan DIFFERENT layers), so the just-in-time gather
    path (docs/ZERO.md) needs shard_dim >= 1 — and on flagship configs
    ``num_layers % degree == 0`` holds exactly where the JIT gathers
    matter most. GSPMD is indifferent to the dim choice."""
    from .auto_parallel import TensorDistAttr

    size = mesh.get_dim_size(axis)
    ax_idx = mesh.dim_names.index(axis)
    for _, p in model.named_parameters():
        if p._dist_attr is not None:
            taken = any(
                isinstance(pl, Shard) and i == ax_idx
                for i, pl in enumerate(p._dist_attr.placements)
            )
            if taken:
                continue
            placements = list(p._dist_attr.placements)
        else:
            placements = [Replicate() for _ in mesh.dim_names]
        shard_dims = {pl.dim for pl in placements if isinstance(pl, Shard)}
        ndim = p._data.ndim
        order = (list(range(1, ndim)) + [0]) if ndim >= 2 else range(ndim)
        for d in order:
            if d not in shard_dims and p._data.shape[d] % size == 0:
                placements[ax_idx] = Shard(d)
                break
        p._dist_attr = TensorDistAttr(mesh, placements)
    return model


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, **kwargs):
    """Parity: paddle.distributed.sharding.group_sharded_parallel.

    level: "os" (stage1) | "os_g" (stage2) | "p_g_os" (stage3).
    Returns (model, optimizer, scaler) with sharding marks applied; the
    actual partitioning happens when ShardedTrainStep places state on
    the mesh — stage1 shards optimizer slots (shard_opt_states), stage
    2/3 engage the ZeRO execution mode (reduce-scattered grads,
    dp-sharded update, stage-3 just-in-time param gathers) when the
    mesh qualifies, else fall back to GSPMD placements (docs/ZERO.md).
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(
            f"group_sharded_parallel level={level!r}: expected 'os' "
            "(stage 1), 'os_g' (stage 2) or 'p_g_os' (stage 3)")
    if offload:
        # the kwarg used to be silently ignored — pretending CPU offload
        # happened is worse than refusing it (a planner sized for
        # offloaded slots would OOM the chip)
        raise NotImplementedError(
            "group_sharded_parallel(offload=True): CPU offload of "
            "sharded state is not implemented on this runtime. Sharded "
            "state stays in HBM, divided by the sharding degree "
            "(docs/ZERO.md); pass offload=False.")
    if kwargs:
        import warnings

        warnings.warn(
            "group_sharded_parallel: ignoring unknown kwargs "
            f"{sorted(kwargs)} — accepted for reference-API "
            "compatibility, but none of them alter this runtime's "
            "sharding behavior", stacklevel=2)
    from .auto_parallel import get_mesh

    mesh = get_mesh()
    if mesh is None:
        from .fleet import get_fleet_mesh

        mesh = get_fleet_mesh()
    if mesh is None:
        raise RuntimeError("call fleet.init or set_mesh before group_sharded_parallel")
    if level == "p_g_os":
        shard_model_parameters(model, mesh)
    optimizer._group_sharded_level = level
    return model, optimizer, scaler
