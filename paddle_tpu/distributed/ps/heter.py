"""Heterogeneous PS: device-cached hot rows over host sparse tables.

Capability slot: the reference's heter parameter server keeps hot
embedding rows on the accelerator while cold rows live in host/PS memory
(`fluid/framework/fleet/ps_gpu_wrapper.cc`, heter_ps/ — GPU-cached
tables; mixed CPU/GPU training). The TPU-native shape: a worker-side
cache whose storage is ONE jax device array (rows resident in HBM,
gathered by slot index inside the training step), backed by the
replicated/sharded host PSClient for misses.

Coherence: pushes go to the PS (the single source of truth) and
INVALIDATE touched cached rows — the next pull re-fetches the
server-updated values (correct under any server-side optimizer, unlike
applying a local shadow update). Eviction is least-recently-used via an
OrderedDict (O(1) per id); freed slots recycle through a free list.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

__all__ = ["HeterSparseCache", "HeterPSWorker"]


class HeterSparseCache:
    """Device-resident LRU row cache over a PSClient sparse table."""

    def __init__(self, client, table_name, dim, cache_rows=4096,
                 dtype=np.float32):
        import jax.numpy as jnp

        self._jnp = jnp
        self.client = client
        self.table = table_name
        self.dim = int(dim)
        self.capacity = int(cache_rows)
        # id -> slot; OrderedDict order IS the recency order (oldest
        # first); freed slots (push invalidation) recycle via _free
        self._slot_of: OrderedDict[int, int] = OrderedDict()
        self._free = list(range(self.capacity))
        self._store = jnp.zeros((self.capacity, self.dim), dtype)
        self.hits = 0
        self.misses = 0

    # -- internals ---------------------------------------------------------
    def _alloc_slot(self):
        if self._free:
            return self._free.pop()
        _, slot = self._slot_of.popitem(last=False)   # evict LRU
        return slot

    # -- worker API --------------------------------------------------------
    def pull(self, ids):
        """Gather rows for `ids` ([N] int) -> device array [N, dim].

        The output is assembled BEFORE cache insertion (hit rows gathered
        from the device store, miss rows patched from the batched host
        pull), so same-batch evictions can never corrupt the result."""
        jnp = self._jnp
        ids = np.asarray(ids).reshape(-1)
        hit_mask = np.asarray([int(i) in self._slot_of for i in ids])
        self.hits += int(hit_mask.sum())
        self.misses += int((~hit_mask).sum())

        # 1) gather the hits from the device store (slots still valid)
        slots = np.asarray([self._slot_of.get(int(i), 0) for i in ids])
        out = self._store[jnp.asarray(slots)]

        # 2) batched host pull for the misses; patch them into the output
        missing = list(dict.fromkeys(
            int(i) for i, h in zip(ids, hit_mask) if not h))
        if missing:
            pulled = np.asarray(
                self.client.pull_sparse(self.table, np.asarray(missing)))
            row_of = dict(zip(missing, pulled))
            idxs = np.nonzero(~hit_mask)[0]
            patch = np.stack([row_of[int(ids[i])] for i in idxs])
            out = out.at[jnp.asarray(idxs)].set(jnp.asarray(patch))
            # 3) NOW insert the fresh rows (may evict, incl. this batch's
            # hits — harmless, output is already built). When distinct
            # misses exceed capacity, _alloc_slot recycles slots handed
            # out earlier in this same loop — dedupe keeping the LAST
            # write per slot so the scatter has unique indices (duplicate
            # scatter-index ordering is unspecified in XLA) and _store
            # agrees with _slot_of (the earlier id was evicted from it).
            slot_row: dict[int, np.ndarray] = {}
            for rid in missing:
                slot = self._alloc_slot()
                self._slot_of[rid] = slot
                slot_row[slot] = row_of[rid]
            slots_u = list(slot_row)
            self._store = self._store.at[jnp.asarray(slots_u)].set(
                jnp.asarray(np.stack([slot_row[s] for s in slots_u])))

        # 4) refresh recency for surviving hit ids (O(1) each)
        for rid in dict.fromkeys(int(i) for i in ids):
            if rid in self._slot_of:
                self._slot_of.move_to_end(rid)
        return out

    def push(self, ids, grads):
        """Push row grads to the PS and invalidate the touched cache
        rows (source of truth stays server-side); their slots recycle."""
        ids = np.asarray(ids).reshape(-1)
        self.client.push_sparse(self.table, ids, np.asarray(grads))
        for i in dict.fromkeys(int(x) for x in ids):
            slot = self._slot_of.pop(i, None)
            if slot is not None:
                self._free.append(slot)

    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class HeterPSWorker:
    """Worker-side heter-PS orchestrator: one device row-cache per sparse
    table plus a background prefetch pipeline.

    Capability slot: the reference's ps_gpu_wrapper training pipeline
    (fluid/framework/fleet/ps_gpu_wrapper.cc — BuildPull prefetches the
    next pass's rows into GPU memory while the current pass computes,
    PushSparseGrad merges duplicate keys in the sender). TPU-native
    shape: `prefetch(batch)` runs the host-side PS pulls for the NEXT
    batch on a worker thread so they overlap the device step; `get()`
    joins and returns device arrays; `push` merges duplicate ids before
    one RPC per table.
    """

    def __init__(self, client, tables, cache_rows=4096):
        """tables: {name: dim} for every sparse table this worker uses."""
        from concurrent.futures import ThreadPoolExecutor

        self.caches = {name: HeterSparseCache(client, name, dim,
                                              cache_rows=cache_rows)
                       for name, dim in tables.items()}
        # ONE worker thread: cache state is not thread-safe; a single
        # pipeline stage is exactly the reference's pass-ahead depth
        self._pool = ThreadPoolExecutor(max_workers=1)
        self._pending = None

    def prefetch(self, batch_ids):
        """batch_ids: {table: ids}. Issues the pulls on the worker
        thread; returns immediately."""
        if self._pending is not None:
            self._pending.result()  # keep the single-stage discipline

        def _run(snapshot):
            return {t: self.caches[t].pull(ids)
                    for t, ids in snapshot.items()}

        self._pending = self._pool.submit(
            _run, {t: list(ids) for t, ids in batch_ids.items()})

    def get(self):
        """Join the pending prefetch -> {table: device rows}."""
        if self._pending is None:
            raise RuntimeError("get() without a prefetch() in flight")
        out = self._pending.result()
        self._pending = None
        return out

    def _quiesce(self):
        """Caches are single-threaded: any main-thread cache access must
        first join an in-flight prefetch (it stays available to get())."""
        if self._pending is not None:
            self._pending.result()

    def pull(self, table, ids):
        """Synchronous pull (no pipeline)."""
        self._quiesce()
        return self.caches[table].pull(ids)

    def push(self, table, ids, grads):
        """Merge duplicate ids worker-side (one summed row per id, the
        reference's sender-side merge), then one PS push + cache
        invalidation."""
        self._quiesce()
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            return
        grads = np.asarray(grads)
        order = {}
        for i, rid in enumerate(ids):
            order.setdefault(int(rid), []).append(i)
        uniq = list(order)
        merged = np.stack([grads[rows].sum(axis=0)
                           for rows in order.values()])
        self.caches[table].push(np.asarray(uniq), merged)

    def hit_rates(self):
        return {t: c.hit_rate() for t, c in self.caches.items()}

    def shutdown(self):
        if self._pending is not None:
            self._pending.result()
            self._pending = None
        self._pool.shutdown()
