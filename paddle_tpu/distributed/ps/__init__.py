"""Parameter server (dense + sparse tables, push/pull workers).

Parity slot: `paddle/fluid/distributed/ps/` (~35k C++: table storage,
brpc push/pull services, PsService server, fleet PS mode) and the python
layer `paddle/incubate/distributed/fleet/`. The reference PS exists for
CPU sparse workloads (billion-row embeddings, async SGD). TPU-native
redesign keeps the capability but swaps the machinery:

- Tables are numpy state on the server (dense arrays; sparse dict of
  lazily-initialised rows) with the optimizer applied SERVER-side on
  push (async-SGD semantics, `a_sync` strategy).
- Transport is the repo's store-backed RPC (`distributed/rpc`) — the
  same push/pull RPC shape as brpc PsService, minus 35k lines. Dense
  tables are round-robin over servers; sparse rows shard by `id % n`.
- Workers embed a `PSClient`; `sparse_embedding` pulls rows for a
  batch's ids, computes on device, and pushes row gradients back.

This is explicitly the lowest-priority subsystem for TPU dense training
(VERDICT), but the capability is real: multi-server sharding, lazy row
init, server-side SGD/Adagrad, pull/push round-trips, and fleet PS-mode
wiring (`fleet.init_server()/run_server()/init_worker()`).
"""
from __future__ import annotations

import os
import threading
import zlib

import numpy as np

__all__ = [
    "DenseTable",
    "SparseTable",
    "PSServer",
    "PSClient",
    "sparse_embedding_lookup",
    "get_global_server",
    "serve_forever",
]


class DenseTable:
    """A dense parameter block; optimizer applied on push (downpour SGD)."""

    def __init__(self, name, shape, init=None, lr=0.01, optimizer="sgd"):
        self.name = name
        # np.array (not asarray): the table must OWN its buffer — a view
        # of the caller's array would let worker-side in-place updates
        # mutate the server state without a push
        self.value = (np.zeros(shape, np.float32) if init is None
                      else np.array(init, np.float32).reshape(shape))
        self.lr = lr
        self.optimizer = optimizer
        self._accum = np.zeros_like(self.value) if optimizer == "adagrad" else None
        # applied-update counter for replica anti-entropy: a replica that
        # missed pushes while down has a LOWER version; resync copies the
        # longest history over (reference: brpc_ps table versioning)
        self.version = 0
        self._digest_vec = None
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def _digest_locked(self):
        """Cheap position-sensitive content fingerprint: detects
        replicas whose COUNTERS agree but whose histories diverged (each
        missed a different push). Projection onto a fixed name-seeded
        random vector — a plain sum is blind to permuted updates.
        Caller must hold self._lock."""
        if (self._digest_vec is None
                or self._digest_vec.size != self.value.size):
            rng = np.random.default_rng(zlib.crc32(self.name.encode()))
            self._digest_vec = rng.standard_normal(self.value.size)
        return float(np.dot(self.value.reshape(-1).astype(np.float64),
                            self._digest_vec))

    def digest(self):
        with self._lock:
            return self._digest_locked()

    def push(self, grad, want_digest=False):
        """Apply the update; return (version, digest|None) ATOMICALLY
        under the table lock — a concurrent pusher can never observe a
        mismatched pair (which would trigger spurious anti-entropy
        resyncs overwriting a healthy replica). The O(N) digest is
        computed only when the caller replicates (want_digest)."""
        grad = np.asarray(grad, np.float32).reshape(self.value.shape)
        with self._lock:
            if self.optimizer == "adagrad":
                self._accum += grad * grad
                self.value -= self.lr * grad / (np.sqrt(self._accum) + 1e-10)
            else:
                self.value -= self.lr * grad
            self.version += 1
            return (self.version,
                    self._digest_locked() if want_digest else None)

    def add_delta(self, delta, want_digest=False):
        """Geo-SGD accumulation: the server SUMS worker deltas (the
        reference's geo strategy applies raw parameter diffs, not
        optimizer steps — ps/service geo mode)."""
        delta = np.asarray(delta, np.float32).reshape(self.value.shape)
        with self._lock:
            self.value += delta
            self.version += 1
            return (self.version,
                    self._digest_locked() if want_digest else None)


class SparseTable:
    """id -> row table with lazy initialisation (the big-embedding case)."""

    def __init__(self, name, dim, lr=0.01, optimizer="sgd",
                 initializer="uniform", init_scale=0.01, seed=0):
        self.name = name
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self.rows = {}
        self._accum = {}
        self._rng = np.random.default_rng(seed)
        self._init = initializer
        self._scale = init_scale
        self._lock = threading.Lock()

    def _new_row(self):
        if self._init == "zeros":
            return np.zeros(self.dim, np.float32)
        return self._rng.uniform(
            -self._scale, self._scale, self.dim).astype(np.float32)

    def pull(self, ids):
        ids = np.asarray(ids).reshape(-1)
        with self._lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, r in enumerate(ids):
                key = int(r)
                if key not in self.rows:
                    self.rows[key] = self._new_row()
                out[i] = self.rows[key]
        return out

    def push(self, ids, grads):
        """Duplicate ids in one push are accumulated before the update
        (the reference merges gradients by key in the worker sender)."""
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        merged = {}
        for r, g in zip(ids, grads):
            merged.setdefault(int(r), np.zeros(self.dim, np.float32))
            merged[int(r)] += g
        with self._lock:
            for key, g in merged.items():
                row = self.rows.setdefault(key, self._new_row())
                if self.optimizer == "adagrad":
                    acc = self._accum.setdefault(
                        key, np.zeros(self.dim, np.float32))
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + 1e-10)
                else:
                    row -= self.lr * g

    def size(self):
        with self._lock:
            return len(self.rows)


class PSServer:
    """Holds tables; the request surface of the reference PsService."""

    def __init__(self, index=0):
        self.index = index
        self.tables = {}
        self._lock = threading.Lock()

    # table management -------------------------------------------------
    def create_dense_table(self, name, shape, **kw):
        with self._lock:
            if name not in self.tables:
                self.tables[name] = DenseTable(name, shape, **kw)
                self._load_table(name, self.tables[name])
        return True

    def create_sparse_table(self, name, dim, **kw):
        with self._lock:
            if name not in self.tables:
                self.tables[name] = SparseTable(name, dim, **kw)
                self._load_table(name, self.tables[name])
        return True

    # PsService verbs ---------------------------------------------------
    def pull_dense(self, name):
        return self.tables[name].pull()

    def push_dense(self, name, grad, want_digest=False):
        return self.tables[name].push(grad, want_digest=want_digest)

    def push_dense_delta(self, name, delta, want_digest=False):
        return self.tables[name].add_delta(delta, want_digest=want_digest)

    def dense_state(self, name):
        """(value, accum, version) snapshot for anti-entropy resync."""
        t = self.tables[name]
        with t._lock:
            return (t.value.copy(),
                    None if t._accum is None else t._accum.copy(),
                    t.version)

    def set_dense_state(self, name, value, accum, version):
        """Overwrite a stale replica from the longest-history snapshot."""
        t = self.tables[name]
        with t._lock:
            t.value = np.array(value, np.float32).reshape(t.value.shape)
            if accum is not None and t._accum is not None:
                t._accum = np.array(accum, np.float32).reshape(
                    t._accum.shape)
            t.version = int(version)
        return True

    def pull_sparse(self, name, ids):
        return self.tables[name].pull(ids)

    def push_sparse(self, name, ids, grads):
        self.tables[name].push(ids, grads)
        return True

    def save(self, dirname):
        """Persist values AND optimizer accumulators per table."""
        os.makedirs(dirname, exist_ok=True)
        for name, t in self.tables.items():
            if isinstance(t, DenseTable):
                np.savez(os.path.join(dirname, f"{name}.dense.npz"),
                         value=t.value,
                         accum=(t._accum if t._accum is not None
                                else np.zeros((0,), np.float32)))
            else:
                ids = np.array(sorted(t.rows), np.int64)
                vals = np.stack([t.rows[i] for i in ids]) if len(ids) else \
                    np.zeros((0, t.dim), np.float32)
                accums = np.stack(
                    [t._accum.get(i, np.zeros(t.dim, np.float32))
                     for i in ids]) if len(ids) else \
                    np.zeros((0, t.dim), np.float32)
                np.savez(os.path.join(dirname, f"{name}.sparse.npz"),
                         ids=ids, vals=vals, accums=accums)
        return True

    def load(self, dirname):
        """Restore existing tables from `dirname`, and remember it so
        tables created LATER (the usual init_server-before-create order)
        pick up their saved state on creation."""
        self._pending_load = dirname
        for name, t in self.tables.items():
            self._load_table(name, t)
        return True

    def _load_table(self, name, t):
        dirname = getattr(self, "_pending_load", None)
        if dirname is None:
            return
        if isinstance(t, DenseTable):
            p = os.path.join(dirname, f"{name}.dense.npz")
            if os.path.exists(p):
                z = np.load(p)
                t.value = z["value"]
                if z["accum"].size:
                    t._accum = z["accum"]
        else:
            p = os.path.join(dirname, f"{name}.sparse.npz")
            if os.path.exists(p):
                z = np.load(p)
                t.rows = {int(i): v for i, v in zip(z["ids"], z["vals"])}
                if "accums" in z.files and z["accums"].size:
                    t._accum = {int(i): a for i, a in
                                zip(z["ids"], z["accums"])}


# -- process-global server (the rpc handlers dispatch here) -----------------
_GLOBAL_SERVER = None


def get_global_server() -> PSServer:
    global _GLOBAL_SERVER
    if _GLOBAL_SERVER is None:
        _GLOBAL_SERVER = PSServer()
    return _GLOBAL_SERVER


# module-level rpc handlers: pickled by reference, executed server-side
def _rpc_create_dense(name, shape, kw):
    return get_global_server().create_dense_table(name, shape, **kw)


def _rpc_create_sparse(name, dim, kw):
    return get_global_server().create_sparse_table(name, dim, **kw)


def _rpc_pull_dense(name):
    return get_global_server().pull_dense(name)


def _rpc_push_dense(name, grad, want_digest=False):
    return get_global_server().push_dense(name, grad,
                                          want_digest=want_digest)


def _rpc_push_dense_delta(name, delta, want_digest=False):
    return get_global_server().push_dense_delta(name, delta,
                                                want_digest=want_digest)


def _rpc_pull_sparse(name, ids):
    return get_global_server().pull_sparse(name, ids)


def _rpc_push_sparse(name, ids, grads):
    return get_global_server().push_sparse(name, ids, grads)


def _rpc_dense_state(name):
    return get_global_server().dense_state(name)


def _rpc_set_dense_state(name, value, accum, version):
    return get_global_server().set_dense_state(name, value, accum, version)


def _rpc_save(dirname):
    return get_global_server().save(dirname)


_STOP_EVENT = threading.Event()


def _rpc_stop():
    """Remote shutdown verb (PsService stop_server): unparks
    serve_forever in the server process."""
    _STOP_EVENT.set()
    return True


def serve_forever(stop_event=None, poll_interval=0.5):
    """Run-server loop (fleet.run_server): the rpc poller thread already
    executes requests; parks until a local stop_event or the remote
    `_rpc_stop` verb fires."""
    import time

    while not _STOP_EVENT.is_set() and (
            stop_event is None or not stop_event.is_set()):
        time.sleep(poll_interval)


class PSClient:
    """Worker-side stub: shards tables over servers, moves numpy.

    `servers` is a list of rpc worker names (cross-process mode) or
    PSServer objects (in-process mode — unit tests, single-node runs).
    Dense tables land on `hash(name) % n`; sparse rows shard `id % n`.

    ``replication=r`` keeps every dense table on r consecutive servers
    (fault tolerance: pushes fan out to all live replicas, pulls fail
    over down the replica chain — the reference PS's table replication,
    fluid/distributed/ps/service). Anti-entropy (r4): every push returns
    the table's applied-update version; when live replicas disagree, the
    longest history is copied over the stale ones, so a replica that
    missed pushes while TRANSIENTLY down converges on the next
    successful push round instead of silently serving stale state on a
    later failover (reference: brpc_ps_server table versioning). Durable
    recovery remains the save()/load() path.
    """

    def __init__(self, servers, replication=1):
        if not servers:
            raise ValueError("PSClient needs at least one server")
        self.servers = list(servers)
        self.n = len(self.servers)
        self.replication = max(1, min(int(replication), self.n))

    def _call(self, idx, fn, *args):
        target = self.servers[idx]
        if isinstance(target, PSServer):
            local = {
                _rpc_create_dense: lambda n_, s_, k_: target.create_dense_table(n_, s_, **k_),
                _rpc_create_sparse: lambda n_, d_, k_: target.create_sparse_table(n_, d_, **k_),
                _rpc_pull_dense: target.pull_dense,
                _rpc_push_dense: target.push_dense,
                _rpc_push_dense_delta: target.push_dense_delta,
                _rpc_pull_sparse: target.pull_sparse,
                _rpc_push_sparse: target.push_sparse,
                _rpc_dense_state: target.dense_state,
                _rpc_set_dense_state: target.set_dense_state,
                _rpc_save: target.save,
                _rpc_stop: lambda: True,  # in-process server: nothing parked
            }
            return local[fn](*args)
        from ..rpc import rpc_sync

        return rpc_sync(target, fn, args=args)

    def _dense_server(self, name):
        # stable across processes (str hash is PYTHONHASHSEED-randomized)
        return zlib.crc32(name.encode()) % self.n

    def _dense_replicas(self, name):
        base = self._dense_server(name)
        return [(base + i) % self.n for i in range(self.replication)]

    # dense -------------------------------------------------------------
    def create_dense_table(self, name, shape, **kw):
        out, ok, last_err = None, False, None
        for idx in self._dense_replicas(name):
            try:
                out = self._call(idx, _rpc_create_dense, name, shape, kw)
                ok = True
            except Exception as e:  # same best-effort contract as pushes
                last_err = e
        if not ok:
            raise last_err
        return out

    def pull_dense(self, name):
        last_err = None
        for idx in self._dense_replicas(name):
            try:
                return self._call(idx, _rpc_pull_dense, name)
            except Exception as e:  # replica down: fail over
                last_err = e
        raise last_err

    def _push_replicated(self, name, fn, *payload):
        ok, last_err, versions = False, None, {}
        for idx in self._dense_replicas(name):
            try:
                versions[idx] = self._call(idx, fn, name, *payload)
                ok = True
            except Exception as e:  # dead replica: best-effort continue
                last_err = e
        if not ok:
            raise last_err
        # anti-entropy: push RPCs return (applied-update counter, value
        # digest). Replicas that rejoined after missing pushes report a
        # LOWER counter; replicas that each missed a DIFFERENT push tie
        # on the counter but differ in digest. Either way the stale
        # copies are overwritten so a later failover can never serve
        # divergent state (VERDICT r3 item 8; reference:
        # brpc_ps_server table versioning). On a counter tie the
        # lowest-index replica wins deterministically — convergence over
        # exactness, the reference's best-effort contract. Resync itself
        # is best-effort too: a replica dying mid-resync must not crash
        # a push that succeeded on every live replica.
        live = {i: v for i, v in versions.items()
                if isinstance(v, tuple) and len(v) == 2}
        if len(live) > 1 and len(set(live.values())) > 1:
            try:
                self._anti_entropy(name, live)
            except Exception:
                pass
        return True

    def _anti_entropy(self, name, live_versions):
        # highest counter wins; counter ties break to the LOWEST replica
        # index (deterministic across workers)
        newest = max(live_versions, key=lambda i: (live_versions[i][0], -i))
        value, accum, version = self._call(newest, _rpc_dense_state, name)
        src_digest = live_versions[newest][1]
        for idx, (v, digest) in live_versions.items():
            if idx == newest:
                continue
            if v < version or digest != src_digest:
                self._call(idx, _rpc_set_dense_state, name, value, accum,
                           version)

    def push_dense(self, name, grad):
        # the O(N) digest is requested only when replication needs it
        return self._push_replicated(name, _rpc_push_dense,
                                     np.asarray(grad),
                                     self.replication > 1)

    def push_dense_delta(self, name, delta):
        """Geo-SGD verb: server ADDS the raw parameter delta."""
        return self._push_replicated(name, _rpc_push_dense_delta,
                                     np.asarray(delta),
                                     self.replication > 1)

    # sparse ------------------------------------------------------------
    def create_sparse_table(self, name, dim, **kw):
        for i in range(self.n):
            self._call(i, _rpc_create_sparse, name, dim, kw)
        return True

    def pull_sparse(self, name, ids):
        ids = np.asarray(ids).reshape(-1)
        parts = {}
        for i in range(self.n):
            sel = np.nonzero(ids % self.n == i)[0]
            if len(sel):
                parts[i] = (sel, self._call(i, _rpc_pull_sparse, name,
                                            ids[sel]))
        dim = next(iter(parts.values()))[1].shape[1] if parts else 0
        out = np.zeros((len(ids), dim), np.float32)
        for sel, vals in parts.values():
            out[sel] = vals
        return out

    def push_sparse(self, name, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        for i in range(self.n):
            sel = np.nonzero(ids % self.n == i)[0]
            if len(sel):
                self._call(i, _rpc_push_sparse, name, ids[sel], grads[sel])
        return True

    def save(self, dirname):
        for i in range(self.n):
            self._call(i, _rpc_save, os.path.join(dirname, f"server{i}"))
        return True

    def stop_servers(self):
        """fleet.stop_worker(): release every server's run_server park."""
        for i in range(self.n):
            self._call(i, _rpc_stop)
        return True


def sparse_embedding_lookup(client: PSClient, table: str, ids, dim: int):
    """Distributed embedding lookup returning a device tensor whose
    backward pushes row grads to the table (the sparse_embedding op).

    Eager: pull -> to device; caller computes loss and calls
    `push_sparse_grad(client, table, ids, grad_rows)` with the rows'
    gradient (obtained from autograd on the returned tensor)."""
    import paddle_tpu as paddle

    rows = client.pull_sparse(table, np.asarray(ids).reshape(-1))
    t = paddle.to_tensor(rows.reshape(list(np.asarray(ids).shape) + [dim]))
    t.stop_gradient = False
    return t


def push_sparse_grad(client: PSClient, table: str, ids, grad):
    g = np.asarray(grad, np.float32)
    ids = np.asarray(ids).reshape(-1)
    return client.push_sparse(table, ids, g.reshape(len(ids), -1))
