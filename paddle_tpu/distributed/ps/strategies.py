"""Parameter-server training strategies: async and geo.

Capability parity: the reference's the_one_ps modes
(``python/paddle/distributed/ps/`` + ``fluid/distributed/ps/service``):
- **sync**: every worker pushes, a barrier, then everyone pulls — that is
  the default PSClient flow (callers order the calls).
- **async** (downpour): pushes are fire-and-forget — the server applies
  updates as they arrive, pulls read possibly-stale values; workers never
  barrier. `AsyncPSClient` gives a PSClient that queues pushes onto a
  background sender thread.
- **geo** (Geo-SGD): each worker trains a LOCAL replica with its own
  optimizer; every ``geo_step`` steps it pushes the parameter DELTA
  (local - base) to the server, pulls the fresh global value and rebases.
  `GeoSGDWorker` implements the worker-side protocol over the
  ``push_dense_delta`` verb.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["AsyncPSClient", "GeoSGDWorker"]


class AsyncPSClient:
    """Non-blocking push wrapper: a background thread drains the send
    queue in order; `flush()` waits until everything pushed so far has
    been applied server-side (the reference's async-mode semantics —
    pulls may observe stale parameters between flushes)."""

    def __init__(self, client, max_queue=1024):
        self._client = client
        self._q = queue.Queue(maxsize=max_queue)
        self._err = None
        self._closed = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            fn, args = item
            try:
                fn(*args)
            except Exception as e:  # surfaced on next flush/push
                self._err = e
            finally:
                self._q.task_done()

    def _check(self):
        if self._closed:
            raise RuntimeError("AsyncPSClient is shut down")
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    # -- async verbs ------------------------------------------------------
    def push_dense(self, name, grad):
        self._check()
        self._q.put((self._client.push_dense, (name, np.asarray(grad))))

    def push_sparse(self, name, ids, grads):
        self._check()
        self._q.put((self._client.push_sparse,
                     (name, np.asarray(ids), np.asarray(grads))))

    def flush(self):
        """Barrier for THIS worker's outstanding pushes."""
        self._q.join()
        self._check()

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    # -- pass-through (synchronous) verbs ---------------------------------
    def __getattr__(self, name):
        return getattr(self._client, name)


class GeoSGDWorker:
    """Worker half of Geo-SGD over dense tables.

    Usage::

        worker = GeoSGDWorker(client, {"w": w0_numpy}, geo_step=8)
        for batch in data:
            worker.params["w"] -= lr * local_grad(batch)   # any local opt
            worker.step()                                  # maybe syncs

    Every ``geo_step`` local steps: push ``local - base`` (the server
    sums deltas from all workers), pull the fresh global value, rebase.
    """

    def __init__(self, client, init_params: dict, geo_step=8,
                 create_tables=True):
        self.client = client
        self.geo_step = int(geo_step)
        self.params = {k: np.array(v, np.float32)
                       for k, v in init_params.items()}
        self._base = {k: v.copy() for k, v in self.params.items()}
        self._local_steps = 0
        if create_tables:
            for k, v in self.params.items():
                client.create_dense_table(k, v.shape, init=v)

    def step(self):
        """Count one local optimizer step; sync when the period elapses."""
        self._local_steps += 1
        if self._local_steps % self.geo_step == 0:
            self.sync()

    def sync(self):
        """Push deltas, pull the merged globals, rebase the local copy."""
        for k, local in self.params.items():
            delta = local - self._base[k]
            self.client.push_dense_delta(k, delta)
        for k in self.params:
            fresh = np.asarray(self.client.pull_dense(k), np.float32)
            self.params[k] = fresh.copy()
            self._base[k] = fresh.copy()
