"""paddle.DataParallel (parity: python/paddle/distributed/parallel.py:219).

TPU-native: no EagerReducer/bucketed NCCL allreduce — wrapping marks the
intent; gradient reduction happens inside the compiled step where GSPMD
emits a single fused psum over the dp axis (the XLA equivalent of the
reference's bucket-fused allreduce, reducer.h:88). Eager fallback when a
multi-device dp mesh is active: average grads across the dp axis after
backward via the collective API.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Average grads over the dp world (fused_allreduce_gradients
        parity). No-op in single-process SPMD where psum is compiled in."""
        from . import get_world_size

        if get_world_size() <= 1:
            return
        from . import all_reduce
        from .communication import ReduceOp

        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.AVG)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
