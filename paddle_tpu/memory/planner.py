"""XLA-memory-driven batch/remat auto-planner.

Every round since r3 hand-tuned the bench batch and remat name list
against OOMs ("b5 OOMs" comments in bench.py). But
``jit(...).lower().compile().memory_analysis()`` tells us the exact HBM
budget of any candidate (batch, remat-policy) TrainStep WITHOUT executing
it — the same buffer-assignment numbers the XLA weight-update-sharding
work (arXiv:2004.13336) converts into throughput. The planner lowers the
candidate grid ahead of time, rejects configs whose peak exceeds the chip
budget, and picks the best fit by a throughput estimate — so bench.py
stops carrying hand-set caps and a chip upgrade re-plans itself.

Planning cost is compile time (one AOT compile per candidate evaluated,
highest-score first, stopping at the first fit); decisions are cached on
disk keyed by (config hash, chip, device count, budget, grid), so only
the first run per configuration pays.

Knobs (docs/MEMORY.md):
- ``PTPU_HBM_BUDGET``: override the per-chip budget (GB when < 1024,
  bytes otherwise).
- ``PTPU_PLAN_CACHE``: decision-cache path; ``0`` disables caching.

Telemetry gauges set on every decision: ``hbm_peak_bytes``,
``act_saved_bytes``, ``act_int8_bytes``.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from .. import telemetry as _telemetry
from .int8_ckpt import int8_saved_nbytes, parse_save_names

_HBM_PEAK = _telemetry.gauge(
    "hbm_peak_bytes",
    "planner-chosen train-step peak HBM (XLA buffer assignment: "
    "argument + temp bytes)")
_ACT_SAVED = _telemetry.gauge(
    "act_saved_bytes",
    "estimated bytes of remat-saved activations per step under the "
    "chosen policy (all layers)")
_ACT_INT8 = _telemetry.gauge(
    "act_int8_bytes",
    "estimated bytes of int8-saved activations (+fp32 scales) within "
    "act_saved_bytes")
_PLAN_EVALS = _telemetry.counter(
    "memory_plan_lowerings_total",
    "candidate TrainStep programs lowered+compiled by the planner",
    # fit | over_budget | error | cache_hit | memoized ("memoized" =
    # a build SAVED because an earlier candidate already lowered the
    # same traced program; fit+over_budget+error = actual lowerings)
    labelnames=("outcome",))


class MemoryPlanError(RuntimeError):
    """No candidate fits the HBM budget."""


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point of the batch x remat x head-chunk x depth grid.
    ``score`` overrides the default throughput estimate (higher =
    preferred). ``head_chunk`` is the fused-CE vocab-chunk size (None =
    the kernel default) — larger chunks mean fewer serialized LSE scan
    steps but a bigger resident [tokens, chunk] fp32 block, so it trades
    against batch/remat inside the same HBM budget. ``depth`` is a
    num_layers override for callers whose step_factory rebuilds the
    model per candidate — with scan-over-layers compilation flat in
    depth (docs/SCAN.md), depth sweeps cost one cheap AOT compile per
    point instead of a depth-linear trace."""
    batch: int
    policy: str
    score: float | None = None
    head_chunk: int | None = None
    depth: int | None = None
    #: quant-compute site spec ("all"/"attn"/"ffn"/comma-joined sites,
    #: None = wide GEMMs): the step_factory appends the matching
    #: ``quant:`` entries to the candidate's names: policy, making
    #: narrow-vs-wide compute a planner axis like batch x remat
    #: (docs/QUANT.md)
    quant: str | None = None


@dataclasses.dataclass
class PlanDecision:
    batch: int
    policy: str
    peak_bytes: int
    budget_bytes: int
    fits: bool
    score: float
    source: str          # "planner" | "cache" | "env-override"
    chip: str
    key: str
    act_saved_bytes: int | None = None
    act_int8_bytes: int | None = None
    opt_state_bytes: int | None = None
    candidates: list = dataclasses.field(default_factory=list)
    head_chunk: int | None = None
    depth: int | None = None
    #: winning candidate's quant-compute site spec (Candidate.quant) —
    #: the caller re-applies it to the policy it builds with
    quant: str | None = None
    #: ZeRO pricing record (docs/ZERO.md): {"stage", "degree", analytic
    #: byte pools, "hbm_savings_bytes"} — None when no zero info passed
    zero: dict | None = None

    def as_json(self):
        """The bench JSON ``"memory"`` block (docs/MEMORY.md contract)."""
        return dataclasses.asdict(self)


# -- budget -----------------------------------------------------------------
#: per-chip HBM when the backend doesn't report bytes_limit
_CHIP_HBM = (("v5p", 95e9), ("v5 lite", 16e9), ("v5e", 16e9),
             ("trillium", 32e9), ("v6", 32e9), ("v4", 32e9))


def chip_kind():
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def hbm_budget_bytes(budget=None):
    """Resolve the HBM budget: PTPU_HBM_BUDGET env (GB if < 1024, bytes
    otherwise) > explicit arg > backend bytes_limit > chip table > 16GB."""
    env = os.environ.get("PTPU_HBM_BUDGET")
    if env:
        v = float(env)
        return int(v * 2**30) if v < 1024 else int(v)
    if budget is not None:
        return int(budget)
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
        if stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    kind = chip_kind().lower()
    for k, v in _CHIP_HBM:
        if k in kind:
            return int(v)
    return int(16e9)


# -- throughput estimate ----------------------------------------------------
# Fraction of one decoder block's forward FLOPs the backward replay SKIPS
# when the anchor is saved (models/gpt.py _block_pure tags). Heuristic
# weights fit to the r3-r5 sweeps (attention kernel ~ a fifth of the
# block, gate+up ~ a third); they only need to rank policies, not predict
# absolute MFU.
_ANCHOR_COVERAGE = {
    "attn_res": 0.18, "attn_lse": 0.02, "attn_out": 0.20,
    "attn_q": 0.07, "attn_k": 0.055, "attn_v": 0.055,
    "resid_mid": 0.09, "ln2_out": 0.01, "rms_rstd": 0.01,
    "ffn_gate": 0.17, "ffn_up": 0.17, "ffn_out": 0.04,
}
#: int8 saves skip the same recompute but pay quant/dequant bandwidth
_INT8_DISCOUNT = 0.9
_POLICY_COVERAGE = {"none": 1.0, "full": 0.0, "dots": 0.6,
                    "attn": 0.22, "attn_ffn": 0.26}


def policy_coverage(policy):
    """~fraction of forward FLOPs the backward replay skips under
    ``policy`` (a recompute_policy string)."""
    pol = str(policy)
    if pol in _POLICY_COVERAGE:
        return _POLICY_COVERAGE[pol]
    if pol.startswith("names:"):
        _, int8_names = parse_save_names(pol[len("names:"):])
        cov = 0.0
        for raw in pol[len("names:"):].split(","):
            nm = raw.strip()
            base = nm[len("int8:"):] if nm.startswith("int8:") else nm
            w = _ANCHOR_COVERAGE.get(base, 0.0)
            cov += w * (_INT8_DISCOUNT if base in int8_names else 1.0)
        return min(cov, 0.95)
    return 0.0


def throughput_score(batch, policy, head_chunk=None):
    """MFU-shaped estimate: useful FLOPs per token are 3F (fwd+bwd), the
    replay re-runs (1 - coverage)F of them, and larger batches buy mildly
    better MXU efficiency. Calibrated on r4/r5: b3 + full ffn saves must
    outrank b4 without them (measured 0.5629 vs 0.5468). A larger CE
    head chunk nudges the score up (fewer serialized LSE scan steps —
    only a ranking tiebreak, the HBM cost is what memory_analysis
    prices)."""
    import math

    cov = policy_coverage(policy)
    score = 3.0 / (4.0 - cov) * (1.0 + 0.03 * int(batch))
    if head_chunk:
        score *= 1.0 + 0.004 * math.log2(max(int(head_chunk), 1) / 1024.0)
    return score


# -- activation-byte estimate (telemetry + bench JSON) ----------------------
def estimate_stacked_activation_bytes(policy, *, num_layers, batch, seq,
                                      hidden, num_heads, num_kv_heads,
                                      intermediate, act_bytes=2,
                                      block=None):
    """(saved_bytes, int8_bytes) the stacked decoder's remat policy pins
    in HBM across all layers — the analytic counterpart of
    ``memory_analysis`` that attributes bytes to NAMES. Unknown anchors
    count 0 (custom-kernel residual shapes vary); non-``names:`` policies
    return (0, 0)."""
    from .int8_ckpt import INT8_BLOCK

    block = block or INT8_BLOCK
    pol = str(policy)
    if not pol.startswith("names:"):
        return 0, 0
    _, int8_names = parse_save_names(pol[len("names:"):])
    hd = hidden // num_heads
    kv = num_kv_heads * hd
    tok = batch * seq
    # elements per layer, with the dtype each anchor is saved in
    elems = {
        "attn_q": (tok * hidden, act_bytes),
        "attn_k": (tok * kv, act_bytes),
        "attn_v": (tok * kv, act_bytes),
        "attn_out": (tok * hidden, act_bytes),
        "attn_res": (tok * hidden, act_bytes),
        "attn_lse": (tok * num_heads, 4),
        "resid_mid": (tok * hidden, act_bytes),
        "ln2_out": (tok * hidden, act_bytes),
        "ffn_gate": (tok * intermediate, act_bytes),
        "ffn_up": (tok * intermediate, act_bytes),
        "ffn_out": (tok * intermediate, act_bytes),
        "rms_rstd": (tok * 2, 4),  # one rstd row-vector per rms (2/block)
    }
    saved = int8 = 0
    for raw in pol[len("names:"):].split(","):
        nm = raw.strip()
        base = nm[len("int8:"):] if nm.startswith("int8:") else nm
        if base not in elems:
            continue
        n, nbytes = elems[base]
        if base in int8_names:
            b = int8_saved_nbytes(n, block)
            int8 += b
            saved += b
        else:
            saved += n * nbytes
    return saved * num_layers, int8 * num_layers


# -- decision cache ---------------------------------------------------------
def _cache_path(path=None):
    if path is not None:
        return path or None
    env = os.environ.get("PTPU_PLAN_CACHE")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "memory_plan.json")


def _cache_load(path):
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except (OSError, ValueError):
        return {}


def _cache_store(path, key, decision):
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        d = _cache_load(path)
        d[key] = decision.as_json()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(d, f)
        os.replace(tmp, path)
    except OSError:
        pass  # cache is an optimization; planning already succeeded


# -- ZeRO stage pricing (docs/ZERO.md) --------------------------------------
def zero_hbm_savings(zero):
    """Per-device bytes a ZeRO stage frees versus the unsharded program:
    slot state divides by the sharding degree from stage 1, gradient
    working set from stage 2, resident params from stage 3. ``zero`` is
    a dict {"stage", "degree", "slot_bytes", "grad_bytes",
    "param_bytes"} — the byte pools are the ANALYTIC sizes of the
    UNSHARDED program the planner measured; pass 0 pools when the
    candidate programs were already compiled on the live sharded mesh
    (their memory_analysis peak is per-device and already divided)."""
    if not zero:
        return 0
    degree = int(zero.get("degree") or 1)
    stage = int(zero.get("stage") or 0)
    if degree <= 1 or stage < 1:
        return 0
    frac = 1.0 - 1.0 / degree
    saved = int(zero.get("slot_bytes") or 0) * frac
    if stage >= 2:
        saved += int(zero.get("grad_bytes") or 0) * frac
    if stage >= 3:
        saved += int(zero.get("param_bytes") or 0) * frac
    return int(saved)


# -- the planner ------------------------------------------------------------
def default_program_key(cand):
    """The candidate axes that change the traced program, conservatively:
    every grid axis. Callers that KNOW two candidates lower to the same
    program pass a coarser ``program_key_fn`` — e.g. bench.py resolves
    the EFFECTIVE CE head chunk (fused_cross_entropy.resolve_vocab_chunk
    clamps to the vocab), so head_chunk values that clamp to the same
    chunk share one lowering instead of re-compiling per spelling."""
    return (cand.batch, cand.policy, getattr(cand, "head_chunk", None),
            getattr(cand, "depth", None), getattr(cand, "quant", None))


def plan_train_step(step_factory, candidates, *, budget_bytes=None,
                    cache_path=None, cache_extra=(), act_bytes_fn=None,
                    opt_state_bytes=None, require_fit=True, zero=None,
                    program_key_fn=None):
    """Pick the best (batch, policy) that fits the HBM budget.

    ``step_factory(candidate) -> (TrainStep, batch_avals)`` builds a step
    for the candidate; the planner lowers+compiles it WITHOUT executing
    (``TrainStep.memory_stats`` over abstract avals — no buffers are
    allocated) and reads the XLA buffer-assignment peak. Candidates are
    tried highest :func:`throughput_score` first; the first fit wins, so
    the common case compiles one program. ``require_fit=False`` accepts
    the top candidate even over budget (the env-override path — trust the
    human, but still record ``fits``).

    ``act_bytes_fn(candidate) -> (saved, int8)`` optionally attributes
    saved-activation bytes for telemetry/the bench JSON.

    ``program_key_fn(candidate)`` names the axes that actually change
    the TRACED program (default :func:`default_program_key` — every grid
    axis). When two candidates map to the same key, the second reuses
    the first's measured memory instead of re-lowering — the saved
    build is counted as ``memory_plan_lowerings_total{outcome=
    "memoized"}`` and the evaluated record carries ``"memoized": true``.

    ``zero`` (docs/ZERO.md): ZeRO stage pricing — slot (stage>=1), grad
    (stage>=2) and param (stage>=3) HBM divide by the sharding degree,
    so a candidate whose raw single-chip peak busts the budget can
    still be ACCEPTED at stage 3 (:func:`zero_hbm_savings` is
    subtracted from every measured peak before the fit check, and the
    record lands in ``PlanDecision.zero``). The cache key carries the
    stage/degree: a decision priced at stage 3 is never replayed for a
    stage-0 build.

    Decisions are cached at ``cache_path`` (default
    ``~/.cache/paddle_tpu/memory_plan.json``, env ``PTPU_PLAN_CACHE``,
    ``0`` disables) keyed by (chip, device count, budget, grid,
    ``cache_extra``); a hit returns without lowering anything.
    """
    import jax

    budget = hbm_budget_bytes(budget_bytes)
    chip = chip_kind()
    try:
        ndev = len(jax.devices())
    except Exception:
        ndev = 1
    order = sorted(
        candidates,
        key=lambda c: (c.score if c.score is not None
                       else throughput_score(c.batch, c.policy,
                                             getattr(c, "head_chunk", None))),
        reverse=True)
    grid = [(c.batch, c.policy, getattr(c, "head_chunk", None),
             getattr(c, "depth", None), getattr(c, "quant", None))
            for c in order]
    # the key must carry the scan/unroll mode: a decision priced under
    # the depth-flat scanned program replayed for an unrolled build (or
    # vice versa) would hand back a config priced against the WRONG
    # program — the same staleness class the mem_envs hardening closed
    # in PR 2 (docs/SCAN.md). Depth rides in per-candidate via `grid`.
    # The mode comes from the ONE resolver the model dispatch uses
    # (lazy import: no cycle — models.gpt pulls memory only in-function)
    from ..models.gpt import scan_layers_enabled

    scan_mode = ("scan" if scan_layers_enabled() else "unrolled",
                 os.environ.get("PTPU_UNROLL_LAYERS", "1"))
    savings = zero_hbm_savings(zero)
    zero_key = (tuple(sorted((k, int(v or 0)) for k, v in zero.items()))
                if zero else None)
    # every quant-compute knob rides in the key: a cached decision priced
    # with wide GEMMs must not replay across a PTPU_QUANT_COMPUTE flip
    # (the same staleness class as scan_mode above — docs/QUANT.md)
    from ..quant import cache_key_knobs as _quant_knobs

    key = hashlib.sha1(repr(
        (chip, ndev, budget, tuple(cache_extra), grid, require_fit,
         scan_mode, zero_key, _quant_knobs())
    ).encode()).hexdigest()[:16]

    cpath = _cache_path(cache_path)
    if cpath:
        hit = _cache_load(cpath).get(key)
        if hit:
            hit = dict(hit, source="cache")
            decision = PlanDecision(**hit)
            _PLAN_EVALS.inc(labels=("cache_hit",))
            _set_gauges(decision)
            return decision

    evaluated = []
    chosen = None
    key_fn = program_key_fn or default_program_key
    lowered = {}  # program key -> measured memory (the memoization seam)
    for cand in order:
        score = (cand.score if cand.score is not None
                 else throughput_score(cand.batch, cand.policy,
                                       getattr(cand, "head_chunk", None)))
        pkey = key_fn(cand)
        memoized = pkey in lowered
        if memoized:
            # an earlier candidate already lowered this exact traced
            # program (e.g. head_chunk spellings clamping to the same
            # effective CE chunk) — reuse its measured bytes, count the
            # saved build
            mem = lowered[pkey]
            _PLAN_EVALS.inc(labels=("memoized",))
        else:
            step, batch_avals = step_factory(cand)
            # label this step's build as a planning compile so the
            # recompile watchdog's per-function counts stay meaningful
            # (jit._build)
            step._planning = True
            try:
                mem = step.memory_stats(*batch_avals)
            except Exception as e:  # lowering/compile failure = not plannable
                _PLAN_EVALS.inc(labels=("error",))
                evaluated.append(
                    {"batch": cand.batch, "policy": cand.policy,
                     "head_chunk": getattr(cand, "head_chunk", None),
                     "depth": getattr(cand, "depth", None),
                     "quant": getattr(cand, "quant", None),
                     "score": score, "error": str(e)[:200]})
                continue
            lowered[pkey] = mem
        # zero pricing: the sharded stages free (1 - 1/degree) of the
        # slot/grad/param pools versus the measured unsharded program
        fits = mem["peak_bytes"] - savings <= budget
        if not memoized:
            _PLAN_EVALS.inc(labels=("fit" if fits else "over_budget",))
        evaluated.append({"batch": cand.batch, "policy": cand.policy,
                          "head_chunk": getattr(cand, "head_chunk", None),
                          "depth": getattr(cand, "depth", None),
                          "quant": getattr(cand, "quant", None),
                          "score": score, "peak_bytes": mem["peak_bytes"],
                          "fits": fits, "memoized": memoized})
        if fits or not require_fit:
            chosen = (cand, mem, score, fits)
            break
    if chosen is None:
        raise MemoryPlanError(
            f"no candidate fits the HBM budget ({budget} bytes on {chip}); "
            f"evaluated: {evaluated}")

    cand, mem, score, fits = chosen
    decision = PlanDecision(
        batch=cand.batch, policy=cand.policy,
        head_chunk=getattr(cand, "head_chunk", None),
        depth=getattr(cand, "depth", None),
        quant=getattr(cand, "quant", None),
        peak_bytes=int(mem["peak_bytes"]), budget_bytes=int(budget),
        fits=bool(fits), score=float(score),
        source="planner" if require_fit else "env-override",
        chip=chip, key=key, opt_state_bytes=opt_state_bytes,
        candidates=evaluated,
        zero=(dict(zero, hbm_savings_bytes=int(savings))
              if zero else None))
    if act_bytes_fn is not None:
        saved, i8 = act_bytes_fn(cand)
        decision.act_saved_bytes = int(saved)
        decision.act_int8_bytes = int(i8)
    _set_gauges(decision)
    if cpath:
        _cache_store(cpath, key, decision)
    return decision


def _set_gauges(decision):
    _HBM_PEAK.set(decision.peak_bytes)
    if decision.act_saved_bytes is not None:
        _ACT_SAVED.set(decision.act_saved_bytes)
    if decision.act_int8_bytes is not None:
        _ACT_INT8.set(decision.act_int8_bytes)
