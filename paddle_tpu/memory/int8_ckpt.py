"""Int8 activation checkpointing: quantized save points for selective remat.

The r3-r5 MFU climb was funded by HBM headroom bought by hand — factored
Adam, the int8 LM head, hand-picked ``save_only_these_names`` lists, and
"b5 OOMs" batch caps in bench.py. Every bf16 activation a remat policy
saves costs ``2 * B * S * dim`` bytes per layer; EQuARX-style blockwise
int8 (arXiv:2506.17615) stores the same residual at ~half that (1 byte of
mantissa + one fp32 scale per 256-elem block) with negligible quality
cost for bandwidth/memory-bound tensors.

``int8_checkpoint(x, name)`` is the save/restore pair: at checkpoint-save
time the tensor is quantized to blockwise int8 (+fp32 scales) and BOTH
pieces are tagged with ``checkpoint_name`` (``int8:<name>`` /
``int8:<name>:scale``); the value flowing downstream is the dequantized
round-trip, so the backward replay rebuilds it from the saved int8 pair
instead of re-running the producing matmuls. A ``custom_vjp`` makes the
round-trip a straight-through estimator — the cotangent passes through
exactly (round() would otherwise zero the gradient), the standard
quantised-training recipe shared with the int8 LM head
(incubate/nn/functional/_int8_head_core).

Exposed through the existing ``recompute_policy`` name syntax: an
``int8:<anchor>`` entry in a ``names:`` policy (parsed by
``parse_save_names``) switches that anchor's save point in
``models/gpt.py::_block_pure`` from a bf16 ``checkpoint_name`` to this
quantized pair. Unlike the exact-forward ``_ffn_i8`` block (whose
hand-written backward is specific to the swiglu FFN), this is generic
over any named anchor; the price is that forward numerics downstream of
the save point see the round-tripped value (the parity test bounds the
end-to-end loss drift <2%, tests/test_memory.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

#: block length for the per-block absmax scales (matches the 8-bit Adam
#: moment blocks, optimizer/__init__.py _Q8_BLOCK)
INT8_BLOCK = 256

#: absmax scale floor shared by every quantizer in the repo (blockwise int8
#: saves here, the serving KV rows, incubate fp8, and paddle_tpu/quant) — an
#: all-zero tensor divides by this instead of 0 and round-trips to exact 0.
SCALE_EPS = 1e-12


def quantize_blockwise_int8(x, block=INT8_BLOCK):
    """Blockwise absmax int8: flatten, pad to a block multiple, one fp32
    scale per ``block`` elements. Returns (q int8 [nb, block], s f32 [nb, 1])."""
    n = x.size
    xf = x.astype(jnp.float32).reshape(-1)
    pad = (-n) % block
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad,), jnp.float32)])
    xb = xf.reshape(-1, block)
    s = jnp.maximum(jnp.max(jnp.abs(xb), axis=-1, keepdims=True) / 127.0,
                    SCALE_EPS)
    q = jnp.clip(jnp.round(xb / s), -127, 127).astype(jnp.int8)
    return q, s


def dequantize_blockwise_int8(q, s, shape, dtype):
    """Inverse of quantize_blockwise_int8 for a tensor of ``shape``/``dtype``."""
    xf = (q.astype(jnp.float32) * s).reshape(-1)
    n = 1
    for d in shape:
        n *= int(d)
    return xf[:n].reshape(shape).astype(dtype)


def quantize_rows_int8(x, eps=SCALE_EPS):
    """Absmax int8 over the LAST axis: one fp32 scale per row.

    The paged-KV grid (docs/SERVING.md): the serving engine's int8 KV
    cache quantizes each (layer, kv-head, page-slot) row of ``head_dim``
    elements independently, so a single-token scatter write updates one
    block and its one scale without re-reading neighbours — the
    :func:`quantize_blockwise_int8` recipe with block = the row the page
    table already addresses. Returns ``(q int8 [..., D], s f32 [..., 1])``.
    """
    xf = x.astype(jnp.float32)
    s = jnp.maximum(
        jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, eps)
    q = jnp.clip(jnp.round(xf / s), -127, 127).astype(jnp.int8)
    return q, s


def dequantize_rows_int8(q, s, dtype=None):
    """Inverse of :func:`quantize_rows_int8`; ``dtype`` casts the result
    (default: stay fp32)."""
    x = q.astype(jnp.float32) * s
    return x if dtype is None else x.astype(dtype)


def int8_saved_nbytes(numel, block=INT8_BLOCK):
    """Bytes one int8-saved tensor of ``numel`` elements holds in HBM
    (int8 payload + fp32 block scales, padding included)."""
    nb = (int(numel) + block - 1) // block
    return nb * block + nb * 4


@functools.lru_cache(maxsize=None)
def _int8_ckpt_fn(name, block):
    """One custom_vjp per (name, block): the tag string must be baked in
    (checkpoint_name takes a static python string), and lru_cache keeps
    the function identity stable so jit caches don't churn per call."""

    def roundtrip(x):
        q, s = quantize_blockwise_int8(x, block)
        q = checkpoint_name(q, f"int8:{name}")
        s = checkpoint_name(s, f"int8:{name}:scale")
        return dequantize_blockwise_int8(q, s, x.shape, x.dtype)

    @jax.custom_vjp
    def f(x):
        return roundtrip(x)

    def fwd(x):
        return roundtrip(x), None

    def bwd(_, g):
        # straight-through: the round-trip is treated as identity by AD
        return (g,)

    f.defvjp(fwd, bwd)
    return f


def int8_checkpoint(x, name, block=INT8_BLOCK):
    """Quantized remat save point. Under ``jax.checkpoint`` with a policy
    saving ``int8:<name>`` + ``int8:<name>:scale`` (what
    ``parse_save_names`` emits for an ``int8:<name>`` entry), the backward
    replay reconstructs this tensor from the saved int8 pair — ~half the
    HBM of a bf16 save. Without such a policy the tags are inert, but the
    forward still sees the round-tripped value."""
    return _int8_ckpt_fn(str(name), int(block))(x)


#: anchors tagged INSIDE custom kernels' vjps (pallas flash / rms /
#: add_rms) — their save points are not routeable through
#: ``int8_checkpoint``, so an ``int8:`` request would silently drop the
#: real save (the anchor recomputes every backward) while claiming the
#: memory win. Reject loudly instead.
KERNEL_ANCHORS = frozenset({"attn_res", "attn_lse", "rms_rstd", "addrms_y"})


def parse_save_names(spec):
    """Parse a comma-separated remat name list with optional ``int8:``
    prefixes (the payload of a ``names:`` recompute_policy).

    ``"attn_q,int8:resid_mid"`` -> (save_names, int8_names) where
    save_names = ("attn_q", "int8:resid_mid", "int8:resid_mid:scale")
    feeds ``jax.checkpoint_policies.save_only_these_names`` and
    int8_names = frozenset({"resid_mid"}) tells the model which anchors
    to route through :func:`int8_checkpoint`.
    """
    save, int8 = [], set()
    for raw in str(spec).split(","):
        nm = raw.strip()
        if not nm:
            continue
        if nm.startswith("int8:"):
            base = nm[len("int8:"):]
            if not base:
                raise ValueError(f"empty int8: entry in remat names {spec!r}")
            if base in KERNEL_ANCHORS:
                raise ValueError(
                    f"int8:{base}: {base!r} is tagged inside a custom "
                    "kernel's vjp and cannot be int8-saved — use the "
                    f"plain name {base!r} (eligible int8 anchors: "
                    "docs/MEMORY.md)")
            int8.add(base)
            save.append(f"int8:{base}")
            save.append(f"int8:{base}:scale")
        else:
            save.append(nm)
    return tuple(save), frozenset(int8)
