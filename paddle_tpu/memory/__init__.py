"""paddle_tpu.memory — HBM-aware training memory management.

Two halves (ISSUE 2, TPU-native extension — the reference's recompute
pass offers only save-full vs re-run and its auto-tuner measures by
RUNNING candidates; here XLA's buffer assignment prices them unexecuted):

1. **Int8 activation checkpointing** (:mod:`.int8_ckpt`): blockwise-int8
   save points for selective remat, exposed through the existing
   ``recompute_policy`` name syntax as ``int8:<anchor>``.
2. **Memory planner** (:mod:`.planner`): lowers (batch x remat-policy)
   TrainStep candidates via ``lower().compile().memory_analysis()``
   without executing them, picks the best throughput estimate that fits
   the HBM budget, caches decisions per (config, chip), and records the
   outcome in telemetry gauges + the bench JSON ``"memory"`` block.

Plus the layer above both (ISSUE 19): the **layout autotuner**
(:mod:`.autotune`) extends the planner grid with the parallelism axes —
mesh degrees over the compose lattice, ZeRO stage, pipeline schedule x
microbatches, comm buckets — prunes non-composable layouts via the
structured ``compose.Reason`` before any trace, scores survivors
lowering-only (roofline + link model + analytic pipeline bubbles), and
returns the built ``ShardedTrainStep`` for the winner
(``autotune_train_step``; docs/AUTOTUNE.md).

See docs/MEMORY.md for the policy syntax, knobs, and JSON contract.
"""
from .autotune import (  # noqa: F401
    LAYOUT_ENV_KNOBS,
    LayoutCandidate,
    LayoutDecision,
    LayoutSearchError,
    autotune_train_step,
    enumerate_layouts,
    flagship_gpt_factory,
    link_bytes_per_sec,
    plan_wire_bytes,
)
from .int8_ckpt import (  # noqa: F401
    INT8_BLOCK,
    KERNEL_ANCHORS,
    SCALE_EPS,
    dequantize_blockwise_int8,
    dequantize_rows_int8,
    int8_checkpoint,
    int8_saved_nbytes,
    parse_save_names,
    quantize_blockwise_int8,
    quantize_rows_int8,
)
from .planner import (  # noqa: F401
    Candidate,
    MemoryPlanError,
    PlanDecision,
    chip_kind,
    default_program_key,
    estimate_stacked_activation_bytes,
    hbm_budget_bytes,
    plan_train_step,
    policy_coverage,
    throughput_score,
    zero_hbm_savings,
)

__all__ = [
    "INT8_BLOCK", "KERNEL_ANCHORS", "SCALE_EPS",
    "quantize_blockwise_int8", "dequantize_blockwise_int8",
    "quantize_rows_int8", "dequantize_rows_int8",
    "int8_checkpoint", "int8_saved_nbytes", "parse_save_names",
    "Candidate", "PlanDecision", "MemoryPlanError", "plan_train_step",
    "hbm_budget_bytes", "chip_kind", "throughput_score", "policy_coverage",
    "estimate_stacked_activation_bytes", "zero_hbm_savings",
    "default_program_key",
    "LayoutCandidate", "LayoutDecision", "LayoutSearchError",
    "LAYOUT_ENV_KNOBS", "autotune_train_step", "enumerate_layouts",
    "flagship_gpt_factory", "link_bytes_per_sec", "plan_wire_bytes",
]
