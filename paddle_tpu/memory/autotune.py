"""Mesh/schedule layout autotuner over the compose lattice.

Every bench config used to hand-pick its parallelism layout (dp/mp/pp/
sep degrees, ZeRO stage, pipeline schedule, microbatch count, comm
buckets) even though the pieces to derive it already existed:
``plan_train_step`` AOT-prices batch x remat candidates without
executing them, ``COMPAT_LATTICE`` knows which plan combinations
compose, and ``compiled_cost_summary`` + ``memory_analysis()`` price
any lowered program. This module closes the loop (the
arXiv:2004.13336 / GC3 exemplars: derive placement from a cost model
instead of per-config folklore):

1. :class:`LayoutCandidate` extends the planner grid with the layout
   axes — (dp, sharding, mp, pp, sep) degrees factoring the device
   count, ZeRO stage, pipeline schedule x microbatch count, comm
   bucket MB — on top of batch/remat/head_chunk/quant.
2. A pruning pass consults the compose lattice BEFORE lowering: each
   hybrid (mp/pp-live) layout shell resolves ``build_composed_plan``
   once (cheap — no trace); a declined shell prunes every candidate on
   it with the structured :class:`~..distributed.collectives.compose.
   Reason`. Only composable candidates pay a lower+compile.
3. Survivors are scored lowering-only (``TrainStep.aot_report``: one
   AOT compile yields XLA ``memory_analysis`` peak AND the roofline
   ``compiled_cost_summary``) by a predicted tokens/sec:
   ``tokens / (compute_s / (1 - pipeline_idle) + wire_bytes / link)``
   with the HBM-budget fit as a hard constraint.
4. The winning :class:`LayoutDecision` caches on disk next to the
   planner's PlanDecision, keyed by (config, chip, device count,
   budget, grids, every engagement-affecting env knob).

Entry point :func:`autotune_train_step` returns the BUILT
``ShardedTrainStep`` for the winning layout plus the decision;
``bench.py --autotune`` routes both headline lines through it
(docs/AUTOTUNE.md).

Knobs:
- ``PTPU_LAYOUT_CACHE``: decision-cache path; ``0`` disables.
- ``PTPU_LINK_GBPS``: override the interconnect bandwidth the comm
  term prices against (GB/s).

Telemetry: ``autotune_candidates_total{verdict,reason}`` (verdict in
pruned | lowered | error; reason = compose Reason value for pruned,
owning lattice row for lowered, "lowering_error" for error) and the
``autotune_search_seconds`` gauge (docs/TELEMETRY.md).
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import time

from .. import telemetry as _telemetry
from .planner import (MemoryPlanError, PlanDecision, _cache_load,
                      _cache_store, chip_kind, hbm_budget_bytes)

_CANDS = _telemetry.counter(
    "autotune_candidates_total",
    "layout candidates examined by the mesh/schedule autotuner, by "
    "verdict (pruned | lowered | error) and structured reason "
    "(compose Reason for pruned, owning lattice row for lowered)",
    labelnames=("verdict", "reason"))
_SEARCH_SECONDS = _telemetry.gauge(
    "autotune_search_seconds",
    "wall seconds the last layout search spent (pruning + lowering + "
    "scoring; 0 on a decision-cache hit)")

#: mesh axes in the fleet topology order the degrees factor over
LAYOUT_AXES = ("dp", "sharding", "mp", "pp", "sep")

#: env knobs that change which plans ENGAGE for a layout — every one
#: rides the decision cache key so a stale decision can't replay across
#: a knob flip (the PR 2 staleness class; docs/AUTOTUNE.md contract)
LAYOUT_ENV_KNOBS = (
    "PTPU_QUANT_COLLECTIVES", "PTPU_COMPOSED", "PTPU_PIPELINE_SCHEDULE",
    "PTPU_ZERO_MODE", "PTPU_ZERO_JIT_GATHER", "PTPU_RING_ATTN",
    "PTPU_SHARDED_HEAD", "PTPU_TP_SEAM", "PTPU_COMM_BUCKET_MB",
    "PTPU_QUANT_PARAM_GATHER", "PTPU_LINK_GBPS", "PTPU_CE_VCHUNK",
)


class LayoutSearchError(MemoryPlanError):
    """No layout candidate is composable, lowerable and within budget."""


@dataclasses.dataclass(frozen=True)
class LayoutCandidate:
    """One point of the layout search space: the mesh degrees (must
    multiply to the searched device count), the ZeRO stage, the
    pipeline schedule axes, the comm bucket cap, and the planner's
    existing batch/remat/head_chunk/quant axes. ``batch`` is rows PER
    DATA SHARD — the global batch is ``batch * data_parallel``, so
    every layout's batch divides its data axes by construction."""

    dp: int = 1
    sharding: int = 1
    mp: int = 1
    pp: int = 1
    sep: int = 1
    zero_stage: int = 0
    pp_schedule: str = "1f1b"
    pp_microbatches: int | None = None
    bucket_mb: int | None = None
    batch: int = 1
    policy: str = "none"
    head_chunk: int | None = None
    quant: str | None = None

    @property
    def device_count(self):
        n = 1
        for a in LAYOUT_AXES:
            n *= int(getattr(self, a))
        return n

    @property
    def data_parallel(self):
        """Product of the batch-sharding axes (dim-0 of the batch)."""
        return self.dp * self.sharding * self.sep

    @property
    def n_micro(self):
        return int(self.pp_microbatches or self.pp)

    @property
    def hybrid(self):
        return self.mp > 1 or self.pp > 1

    def live_axes(self):
        return frozenset(a for a in LAYOUT_AXES
                         if int(getattr(self, a)) > 1)

    def degrees(self):
        return {a: int(getattr(self, a)) for a in LAYOUT_AXES}

    def shell(self):
        """The composability-deciding slice: two candidates on the same
        shell share the compose verdict (batch/remat/head_chunk/bucket
        never change whether a plan engages), so the pruning oracle
        runs once per shell."""
        return (self.dp, self.sharding, self.mp, self.pp, self.sep,
                self.zero_stage,
                self.pp_schedule if self.pp > 1 else None,
                self.n_micro if self.pp > 1 else None)

    def label(self):
        axes = "x".join(f"{a}{getattr(self, a)}" for a in LAYOUT_AXES
                        if int(getattr(self, a)) > 1) or "single"
        parts = [axes, f"z{self.zero_stage}"]
        if self.pp > 1:
            parts.append(f"{self.pp_schedule}@{self.n_micro}")
        if self.bucket_mb:
            parts.append(f"bk{self.bucket_mb}")
        parts.append(f"b{self.batch}")
        if self.head_chunk:
            parts.append(f"hc{self.head_chunk}")
        if self.quant:
            parts.append(f"q-{self.quant}")
        pol = str(self.policy)
        parts.append("r-" + (pol.split(":", 1)[0] if ":" in pol else pol))
        return "/".join(parts)

    def as_json(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LayoutDecision:
    """The search outcome — the bench JSON ``"layout"`` block
    (docs/AUTOTUNE.md contract). ``memory`` embeds a genuine
    :class:`~.planner.PlanDecision` record for the winner (source
    "autotune", batch = GLOBAL rows) so hbm_report / the bench
    ``"memory"`` block work unchanged."""

    layout: dict
    label: str
    predicted_score: float          # predicted tokens/sec
    predicted_step_seconds: float
    peak_bytes: int
    budget_bytes: int
    fits: bool
    source: str                     # "search" | "cache" | "fallback"
    chip: str
    device_count: int
    key: str
    searched: int                   # candidates lowered (incl. baseline)
    pruned_total: int
    pruned_by_reason: dict = dataclasses.field(default_factory=dict)
    search_seconds: float = 0.0
    fallback_reason: str | None = None
    candidates: list = dataclasses.field(default_factory=list)  # top-3
    pruned: list = dataclasses.field(default_factory=list)
    errors: list = dataclasses.field(default_factory=list)
    baseline: dict | None = None
    link: dict | None = None
    memory: dict | None = None

    def as_json(self):
        return dataclasses.asdict(self)

    def fingerprint(self):
        """sha1 over the decision MINUS the volatile fields (wall
        seconds, cache provenance) — two searches of the same config
        must agree on this bitwise (tests/test_autotune.py)."""
        d = self.as_json()
        d.pop("search_seconds", None)
        d.pop("source", None)
        return hashlib.sha1(
            repr(sorted(d.items(), key=lambda kv: kv[0])).encode()
        ).hexdigest()


# -- link model --------------------------------------------------------------
#: per-chip interconnect bytes/sec for the comm term — order-of-
#: magnitude public ICI numbers; the cost model only needs to RANK
#: layouts, not predict absolute step time. CPU/unknown chips get a
#: placeholder flagged in the decision's "link" record.
_CHIP_LINK = (("v5p", 180e9), ("v5e", 90e9), ("v5 lite", 90e9),
              ("trillium", 180e9), ("v6", 180e9), ("v4", 100e9))


def link_bytes_per_sec():
    """(bytes_per_sec, placeholder?) of the inter-chip link:
    ``PTPU_LINK_GBPS`` override > chip table > 10 GB/s placeholder."""
    env = os.environ.get("PTPU_LINK_GBPS")
    if env:
        return float(env) * 1e9, False
    kind = chip_kind().lower()
    for k, v in _CHIP_LINK:
        if k in kind:
            return float(v), False
    return 10e9, True


def plan_wire_bytes(step):
    """Per-step collective payload bytes of the step's RESOLVED plans:
    the active grad-reduce plan's exact + quantized wire bytes
    (GradReducePlan / ZeroPlan / ComposedPlan / ring reduce all share
    the accounting surface) plus the zero plan's param-gather traffic
    (gathers move params OUT of collectives — disjoint from the grad
    bytes the reduce accounting counts)."""
    total = 0
    plan = step.comms_plan() if hasattr(step, "comms_plan") else None
    if plan is not None:
        total += int(plan.exact_bytes) + int(plan.quantized_wire_bytes)
    zp = step.zero_plan() if hasattr(step, "zero_plan") else None
    if zp is not None:
        total += int(getattr(zp, "param_gather_bytes", 0))
    return total


def pipeline_idle_fraction(layout):
    """The schedule's analytic idle fraction — ``pipeline.
    bubble_fraction_model`` with unit phase costs (the measured-cost
    ``bubble_report`` compiles probe programs per call, far too
    expensive per candidate; the analytic budget ranks schedules and
    microbatch counts the same way)."""
    if layout.pp <= 1:
        return 0.0
    from ..distributed.pipeline import bubble_fraction_model

    return float(bubble_fraction_model(layout.n_micro, layout.pp,
                                       schedule=layout.pp_schedule))


# -- search space ------------------------------------------------------------
def default_zero_stage(dp, sharding, mp, pp, sep):
    """The stage the hand-tuned configs converged on per mesh family:
    stage 3 on pure sharding-live data meshes (the config-5 lineage),
    stage 2 under a hybrid with a live data axis (the 10b lineage),
    stage 0 everywhere else (sep-live meshes: the zero mode declines
    them; no data axis: nothing to shard over)."""
    if mp > 1 or pp > 1:
        return 2 if (dp > 1 or sharding > 1) else 0
    if sep > 1:
        return 0
    return 3 if sharding > 1 else 0


def enumerate_layouts(device_count, *, mp_max=2, pp_max=2, sep_max=2,
                      zero_stage_fn=None, schedules=None,
                      microbatches=(None,), bucket_mbs=(None,),
                      batches=(1,), policies=("none",),
                      head_chunks=(None,), quants=(None,)):
    """The default search space: every (dp, sharding, mp, pp, sep)
    factorization of ``device_count`` under the axis caps, each with
    the stage :func:`default_zero_stage` picks (``zero_stage_fn``
    overrides), crossed with the schedule/microbatch grid on pp-live
    shells and the planner's batch/remat/head_chunk/quant grids.
    Off-lattice hybrid shells (e.g. sep live under mp/pp) ARE
    generated — the pruning pass records them with their structured
    decline Reason instead of silently skipping them. Deterministic
    order (the decision must reproduce bitwise across runs)."""
    n = int(device_count)
    stage_fn = zero_stage_fn or default_zero_stage
    divisors = [d for d in range(1, n + 1) if n % d == 0]
    shells = []
    for mp in divisors:
        if mp > mp_max:
            continue
        for pp in (d for d in divisors if (n // mp) % d == 0):
            if pp > pp_max:
                continue
            for sep in (d for d in divisors if (n // (mp * pp)) % d == 0):
                if sep > sep_max:
                    continue
                rem = n // (mp * pp * sep)
                for dp in (d for d in divisors if rem % d == 0):
                    shells.append((dp, rem // dp, mp, pp, sep))
    out = []
    for dp, sharding, mp, pp, sep in sorted(shells):
        stage = int(stage_fn(dp, sharding, mp, pp, sep))
        scheds = (schedules if schedules is not None
                  else (("1f1b",) if pp > 1 else (None,)))
        if pp <= 1:
            scheds, micros = (None,), (None,)
        else:
            micros = microbatches
        for sched in scheds:
            for nm in micros:
                nm_eff = int(nm or pp)
                for bk in bucket_mbs:
                    for b in batches:
                        # the pipeline splits the per-shard batch into
                        # microbatches — round the grid batch up to the
                        # nearest multiple so every pp-live candidate
                        # lowers (score normalizes by tokens, so a
                        # bigger batch doesn't bias the ranking)
                        b_eff = (b if pp <= 1 or b % nm_eff == 0
                                 else b + nm_eff - b % nm_eff)
                        for pol in policies:
                            for hc in head_chunks:
                                for q in quants:
                                    out.append(LayoutCandidate(
                                        dp=dp, sharding=sharding, mp=mp,
                                        pp=pp, sep=sep, zero_stage=stage,
                                        pp_schedule=sched or "1f1b",
                                        pp_microbatches=nm, bucket_mb=bk,
                                        batch=b_eff, policy=pol,
                                        head_chunk=hc, quant=q))
    return out


# -- candidate build ---------------------------------------------------------
@contextlib.contextmanager
def _layout_env(layout):
    """Apply the layout's env-carried knobs around a candidate build
    (knobs are read at BUILD time — bucket_bytes_cap)."""
    saved = {}
    if layout.bucket_mb is not None:
        saved["PTPU_COMM_BUCKET_MB"] = os.environ.get("PTPU_COMM_BUCKET_MB")
        os.environ["PTPU_COMM_BUCKET_MB"] = str(int(layout.bucket_mb))
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _pin_layout_env(layout):
    """Pin the winner's env-carried knobs for the process: the returned
    step (and any program bench builds after it) must honor the decided
    bucket cap — the knob IS part of the layout now."""
    if layout.bucket_mb is not None:
        os.environ["PTPU_COMM_BUCKET_MB"] = str(int(layout.bucket_mb))


def _build_mesh(layout):
    from ..distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": layout.dp, "mp_degree": layout.mp,
        "pp_degree": layout.pp, "sharding_degree": layout.sharding,
        "sep_degree": layout.sep,
    }
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_fleet_mesh()


def _make_step(layout, model, train_fn, optimizer, mesh):
    from ..distributed.parallel_step import ShardedTrainStep

    return ShardedTrainStep(
        model, train_fn, optimizer, mesh,
        shard_opt_states=(layout.zero_stage == 1),
        sharding_stage=(layout.zero_stage or None))


def _build_candidate(layout, model_factory):
    """mesh + factory model + ShardedTrainStep for one candidate (no
    trace, no compile — plan resolution only happens when the caller
    asks)."""
    mesh = _build_mesh(layout)
    model, train_fn, optimizer = model_factory(layout, mesh)
    return _make_step(layout, model, train_fn, optimizer, mesh)


def flagship_gpt_factory(cfg_factory, *, lr=1e-3, seed=0,
                         optimizer_factory=None, amp_bf16=False):
    """``model_factory`` for GPTForCausalLMPipe flagships — the shape
    bench.py and the MULTICHIP dryrun share. ``cfg_factory()`` returns
    a fresh GPTConfig per call; the factory applies the layout's remat/
    head-chunk/schedule axes to it, the layout's placements to the
    decoder (pipeline placements when pp > 1, tp placements when only
    mp > 1), and the ``group_sharded_parallel`` level matching the
    ZeRO stage. ``amp_bf16=True`` mirrors bench.py's TPU build: the
    model constructs under O2 autocast and its params cast to bf16 —
    without it a searched program would be priced in f32 while the
    measured run executes bf16."""
    def factory(layout, mesh):
        import paddle_tpu as paddle
        from ..distributed.parallel_step import group_sharded_parallel
        from ..models.gpt import GPTForCausalLMPipe

        paddle.seed(seed)
        cfg = cfg_factory()
        pol = layout.policy
        if layout.quant and str(pol).startswith("names:"):
            pol = f"{pol},quant:{layout.quant}"
        cfg.recompute = pol != "none"
        cfg.recompute_policy = pol
        cfg.head_chunk = layout.head_chunk
        if layout.pp > 1:
            cfg.pp_schedule = layout.pp_schedule
            # plain attribute — compose reads getattr(cfg,
            # "pp_microbatches", None) or pp
            cfg.pp_microbatches = layout.n_micro
        if amp_bf16:
            import jax.numpy as jnp

            with paddle.amp.auto_cast(enable=True, dtype="bfloat16",
                                      level="O2"):
                model = GPTForCausalLMPipe(cfg)
            for _, p in model.named_parameters():
                p._data = p._data.astype(jnp.bfloat16)
        else:
            model = GPTForCausalLMPipe(cfg)
        if layout.pp > 1:
            model.decoder.apply_pipeline_placements(
                mesh, tp_axis="mp" if layout.mp > 1 else None)
        elif layout.mp > 1:
            model.decoder.apply_tp_placements(mesh, tp_axis="mp")
        if optimizer_factory is not None:
            opt = optimizer_factory(model)
        else:
            opt = paddle.optimizer.AdamW(learning_rate=lr,
                                         parameters=model.parameters())
        if layout.zero_stage:
            level = {1: "os", 2: "os_g", 3: "p_g_os"}[layout.zero_stage]
            model, opt, _ = group_sharded_parallel(model, opt, level)
        return model, (lambda a, b: model.loss(a, b)), opt

    return factory


# -- decision cache ----------------------------------------------------------
def _layout_cache_path(path=None):
    if path is not None:
        return path or None
    env = os.environ.get("PTPU_LAYOUT_CACHE")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                        "layout_plan.json")


def _layout_key(chip, ndev, budget, cache_extra, layouts, baseline,
                require_fit):
    from ..models.gpt import scan_layers_enabled
    from ..quant import cache_key_knobs as _quant_knobs

    grid = tuple(tuple(sorted(l.as_json().items())) for l in layouts)
    base = (tuple(sorted(baseline.as_json().items()))
            if baseline is not None else None)
    knobs = tuple((k, os.environ.get(k, "")) for k in LAYOUT_ENV_KNOBS)
    scan_mode = ("scan" if scan_layers_enabled() else "unrolled",
                 os.environ.get("PTPU_UNROLL_LAYERS", "1"))
    return hashlib.sha1(repr(
        (chip, ndev, budget, tuple(cache_extra), grid, base, require_fit,
         scan_mode, knobs, _quant_knobs())
    ).encode()).hexdigest()[:16]


# -- scoring -----------------------------------------------------------------
def _score(layout, mem, cost, step, seq_len, link_bps):
    """Predicted tokens/sec for a lowered candidate (docs/AUTOTUNE.md
    cost model): roofline compute seconds inflated by the schedule's
    analytic idle fraction, plus the resolved plans' collective bytes
    over the link bandwidth. The HBM fit is checked by the caller —
    this only prices time."""
    tokens = layout.batch * layout.data_parallel * seq_len
    if cost is not None:
        compute_s = float(cost["device_seconds_est"])
    else:
        # no cost analysis from this executable: fall back to a pure
        # bandwidth proxy over the program's working set so ranking
        # still has a compute term (flagged via cost_placeholder)
        from ..jit import _device_peaks

        _, pb, _ = _device_peaks()
        compute_s = float(mem["temp_bytes"] + mem["output_bytes"]) / pb
    idle = pipeline_idle_fraction(layout)
    wire = plan_wire_bytes(step)
    comm_s = wire / link_bps if link_bps > 0 else 0.0
    step_s = compute_s / max(1e-9, 1.0 - idle) + comm_s
    return {
        "label": layout.label(),
        "layout": layout.as_json(),
        "predicted_tokens_per_sec": tokens / max(step_s, 1e-12),
        "predicted_step_seconds": step_s,
        "compute_seconds_est": compute_s,
        "comm_seconds_est": comm_s,
        "idle_fraction": idle,
        "wire_bytes_per_step": int(wire),
        "tokens_per_step": int(tokens),
        "peak_bytes": int(mem["peak_bytes"]),
        "cost_placeholder": cost is None or bool(
            cost.get("peak_model_placeholder")),
    }


# -- the autotuner -----------------------------------------------------------
def autotune_train_step(model_factory, *, seq_len, layouts=None,
                        baseline=None, batch_avals_fn=None,
                        budget_bytes=None, require_fit=True,
                        cache_path=None, cache_extra=(),
                        device_count=None):
    """Search the layout lattice and return ``(step, decision)`` — the
    BUILT :class:`~..distributed.parallel_step.ShardedTrainStep` for
    the winning layout (plans resolved, nothing executed) and the
    :class:`LayoutDecision` record.

    ``model_factory(layout, mesh) -> (model, train_fn, optimizer)``
    builds the model for one candidate with the layout's placements
    and sharding level applied (:func:`flagship_gpt_factory` makes one
    for flagship GPT configs). The search NEVER executes a step: hybrid
    shells resolve ``build_composed_plan`` first (no trace) and only
    composable candidates are lowered (``aot_report`` — one AOT compile
    per survivor, pricing memory and roofline cost together).

    ``baseline`` (a LayoutCandidate) is the hand-picked reference: it
    is always scored through the same cost model (and may legitimately
    win), lands in ``decision.baseline`` for the bench_gate LAYOUT
    gate, and is the fallback layout when no searched candidate fits —
    recorded as ``source="fallback"`` with a structured
    ``fallback_reason``, never silently.

    Decisions cache at ``~/.cache/paddle_tpu/layout_plan.json``
    (``PTPU_LAYOUT_CACHE``; ``0`` disables), keyed by (config, chip,
    device count, budget, grids, every engagement-affecting env knob —
    :data:`LAYOUT_ENV_KNOBS`). A hit rebuilds the winning step without
    searching.
    """
    import jax

    ndev = int(device_count
               or len(jax.devices()))
    budget = hbm_budget_bytes(budget_bytes)
    chip = chip_kind()
    if layouts is None:
        layouts = enumerate_layouts(ndev)
    layouts = list(layouts)
    for l in layouts:
        if l.device_count != ndev:
            raise ValueError(
                f"layout {l.label()} factors {l.device_count} devices, "
                f"searching {ndev}")
        if not l.hybrid and _lattice_owner_for(l) is None:
            raise ValueError(
                f"layout {l.label()} is off every compose-lattice row "
                f"(live axes {sorted(l.live_axes())}, stage "
                f"{l.zero_stage}) — not searchable (docs/AUTOTUNE.md)")
    if baseline is not None and baseline.device_count > ndev:
        raise ValueError(
            f"baseline {baseline.label()} needs {baseline.device_count} "
            f"devices, have {ndev}")
    key = _layout_key(chip, ndev, budget, cache_extra, layouts, baseline,
                      require_fit)
    avals_fn = batch_avals_fn or (
        lambda l: _default_batch_avals(l, seq_len))

    cpath = _layout_cache_path(cache_path)
    if cpath:
        hit = _cache_load(cpath).get(key)
        if hit:
            decision = LayoutDecision(**dict(hit, source="cache"))
            _SEARCH_SECONDS.set(0.0)
            winner = LayoutCandidate(**decision.layout)
            step = _finalize_winner(winner, model_factory)
            return step, decision

    t0 = time.perf_counter()
    link_bps, link_placeholder = link_bytes_per_sec()
    scored = []
    pruned = []
    errors = []
    shell_declines = {}

    def _examine(layout, *, is_baseline=False):
        shell = layout.shell()
        if layout.hybrid and shell in shell_declines:
            reason = shell_declines[shell]
            pruned.append({"label": layout.label(), "reason": reason,
                           "layout": layout.as_json()})
            _CANDS.inc(labels=("pruned", reason))
            return None
        with _layout_env(layout):
            step = _build_candidate(layout, model_factory)
            if layout.hybrid:
                plan = step._ensure_composed_plan()
                if plan is None:
                    from ..distributed.collectives import compose

                    v = compose.last_verdicts().get("composed")
                    reason = (v[1] if v
                              else compose.Reason.UNSPECIFIED.value)
                    shell_declines[shell] = reason
                    pruned.append({"label": layout.label(),
                                   "reason": reason,
                                   "layout": layout.as_json()})
                    _CANDS.inc(labels=("pruned", reason))
                    return None
            # lowering-only pricing: one AOT compile, zero execution
            step._planning = True
            try:
                mem, cost = step.aot_report(*avals_fn(layout))
            except Exception as e:
                errors.append({"label": layout.label(),
                               "error": str(e)[:200]})
                _CANDS.inc(labels=("error", "lowering_error"))
                return None
            _CANDS.inc(labels=("lowered",
                               _lattice_owner_for(layout) or "composed"))
            rec = _score(layout, mem, cost, step, seq_len, link_bps)
            rec["fits"] = mem["peak_bytes"] <= budget
            rec["is_baseline"] = bool(is_baseline)
            scored.append(rec)
            return rec

    seen = set()
    for layout in layouts:
        seen.add(layout.label())
        _examine(layout)
    baseline_rec = None
    if baseline is not None:
        if baseline.label() in seen:
            baseline_rec = next(r for r in scored
                                if r["label"] == baseline.label())
            baseline_rec["is_baseline"] = True
        else:
            baseline_rec = _examine(baseline, is_baseline=True)

    ranked = sorted(scored,
                    key=lambda r: (-r["predicted_tokens_per_sec"],
                                   r["label"]))
    fitting = [r for r in ranked if r["fits"]]
    source, fallback_reason = "search", None
    if fitting:
        win_rec = fitting[0]
    elif not require_fit and ranked:
        win_rec = ranked[0]
        source, fallback_reason = "search", "no_candidate_fit_unenforced"
    elif baseline_rec is not None:
        win_rec = baseline_rec
        source = "fallback"
        fallback_reason = ("no_candidate_lowered" if not ranked
                           else "no_candidate_fit")
    else:
        raise LayoutSearchError(
            f"no layout candidate is composable and within the HBM "
            f"budget ({budget} bytes on {chip}); pruned={len(pruned)} "
            f"errors={errors}")
    winner = LayoutCandidate(**win_rec["layout"])

    by_reason = {}
    for p in pruned:
        by_reason[p["reason"]] = by_reason.get(p["reason"], 0) + 1
    search_seconds = time.perf_counter() - t0
    _SEARCH_SECONDS.set(search_seconds)

    mem_record = PlanDecision(
        batch=winner.batch * winner.data_parallel, policy=winner.policy,
        peak_bytes=int(win_rec["peak_bytes"]), budget_bytes=int(budget),
        fits=bool(win_rec["fits"]),
        score=float(win_rec["predicted_tokens_per_sec"]),
        source="autotune", chip=chip, key=key,
        head_chunk=winner.head_chunk, quant=winner.quant,
        candidates=[{k: r[k] for k in ("label", "peak_bytes", "fits",
                                       "predicted_tokens_per_sec")}
                    for r in ranked[:3]],
        zero=({"stage": winner.zero_stage,
               "degree": winner.data_parallel, "param_bytes": 0,
               "slot_bytes": 0, "grad_bytes": 0, "hbm_savings_bytes": 0}
              if winner.zero_stage else None))
    decision = LayoutDecision(
        layout=winner.as_json(), label=winner.label(),
        predicted_score=float(win_rec["predicted_tokens_per_sec"]),
        predicted_step_seconds=float(win_rec["predicted_step_seconds"]),
        peak_bytes=int(win_rec["peak_bytes"]), budget_bytes=int(budget),
        fits=bool(win_rec["fits"]), source=source, chip=chip,
        device_count=ndev, key=key, searched=len(scored),
        pruned_total=len(pruned), pruned_by_reason=by_reason,
        search_seconds=round(search_seconds, 3),
        fallback_reason=fallback_reason,
        candidates=ranked[:3], pruned=pruned, errors=errors,
        baseline=(dict(baseline_rec) if baseline_rec is not None
                  else None),
        link={"bytes_per_sec": link_bps, "placeholder": link_placeholder},
        memory=mem_record.as_json())
    if cpath:
        _cache_store(cpath, key, decision)
    step = _finalize_winner(winner, model_factory)
    return step, decision


def _lattice_owner_for(layout):
    from ..distributed.collectives import compose

    return compose.lattice_owner(layout.live_axes(),
                                 stage=layout.zero_stage)


def _default_batch_avals(layout, seq_len):
    import jax
    import jax.numpy as jnp

    rows = layout.batch * layout.data_parallel
    return (jax.ShapeDtypeStruct((rows, int(seq_len)), jnp.int32),
            jax.ShapeDtypeStruct((rows, int(seq_len)), jnp.int64))


def _finalize_winner(layout, model_factory):
    """Build the winning step for real: pin the layout's env knobs for
    the process (the decided bucket cap must govern every later build),
    re-init the fleet mesh, and resolve the step's plans (``_build`` —
    trace-free) so the returned object is ready to compile on first
    call."""
    _pin_layout_env(layout)
    step = _build_candidate(layout, model_factory)
    step._build()
    return step
