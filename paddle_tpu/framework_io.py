"""paddle.save / paddle.load (parity: python/paddle/framework/io.py:773,1020).

The reference pickles nested state dicts with tensor payloads
(``_pickle_save``).  Here tensors serialize as plain numpy arrays inside a
np.savez-compatible safetensors-like container: a pickle of the object tree
where each Tensor leaf is replaced by a tagged numpy payload.  Loading never
executes arbitrary reduce hooks for tensor payloads themselves.
"""
from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np

from .core.tensor import Tensor, Parameter


class _TensorPayload:
    """Pickle-safe stand-in for a Tensor: raw bytes + meta."""

    def __init__(self, array: np.ndarray, is_parameter: bool, stop_gradient: bool, name: str):
        self.dtype = array.dtype.str if array.dtype.names is None else "V"
        # bfloat16 etc. have no numpy str codes portable across processes;
        # store via ml_dtypes name
        self.dtype_name = array.dtype.name
        self.shape = array.shape
        self.data = np.ascontiguousarray(array).tobytes()
        self.is_parameter = is_parameter
        self.stop_gradient = stop_gradient
        self.name = name


def _encode(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(
            obj.numpy(), isinstance(obj, Parameter), obj.stop_gradient, obj.name
        )
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_encode(v) for v in obj)
    return obj


def _decode(obj, return_numpy=False):
    from . import dtypes as _dt

    if isinstance(obj, _TensorPayload):
        npd = _dt.convert_dtype(obj.dtype_name).np_dtype
        arr = np.frombuffer(obj.data, dtype=npd).reshape(obj.shape)
        if return_numpy:
            return arr.copy()
        import jax.numpy as jnp

        if obj.is_parameter:
            t = Parameter(jnp.asarray(arr), trainable=not obj.stop_gradient, name=obj.name)
        else:
            t = Tensor(jnp.asarray(arr), stop_gradient=obj.stop_gradient, name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _decode(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_decode(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save"""
    if hasattr(path, "write"):
        pickle.dump(_encode(obj), path, protocol=protocol)
        return
    path = str(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_encode(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    """paddle.load"""
    if hasattr(path, "read"):
        obj = pickle.load(path)
    else:
        with open(str(path), "rb") as f:
            obj = pickle.load(f)
    return _decode(obj, return_numpy=return_numpy)


# -- async save (parity: framework/io.py:94 async_save — serialization
# offloaded to a background worker so the train loop isn't blocked on
# host pickling/IO; device->host copies happen on the caller thread to
# keep a consistent snapshot) ------------------------------------------
_ASYNC_TASKS = []
_ATEXIT_REGISTERED = False
# per-path write sequence: a stalled older writer must not os.replace()
# over a newer completed save to the same destination
_ASYNC_SEQ: dict = {}
_ASYNC_DONE: dict = {}
_ASYNC_LOCK = None


def async_save(obj, path, protocol=4, sync_other_task=False, **configs):
    """paddle.async_save: snapshot now (device->host copy), pickle+write
    in a background thread. Call `clear_async_save_task_queue()` (or the
    next async_save with sync_other_task=True) to join outstanding
    writes before relying on the files.

    Crash-safe: the writer targets a temp file in the destination
    directory and os.replace()s it into place, so the final path never
    holds a truncated checkpoint; an atexit hook joins outstanding
    writers on normal interpreter exit."""
    import threading

    global _ATEXIT_REGISTERED, _ASYNC_LOCK
    if _ASYNC_LOCK is None:
        _ASYNC_LOCK = threading.Lock()
    if not _ATEXIT_REGISTERED:
        import atexit

        atexit.register(clear_async_save_task_queue)
        _ATEXIT_REGISTERED = True
    if sync_other_task:
        clear_async_save_task_queue()
    snapshot = _encode(obj)   # materialise host copies on THIS thread
    seq = None
    if not hasattr(path, "write"):
        with _ASYNC_LOCK:
            seq = _ASYNC_SEQ.get(str(path), 0) + 1
            _ASYNC_SEQ[str(path)] = seq

    def _write():
        if hasattr(path, "write"):
            pickle.dump(snapshot, path, protocol=protocol)
            return
        p = str(path)
        d = os.path.dirname(p)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(snapshot, f, protocol=protocol)
            with _ASYNC_LOCK:
                if _ASYNC_DONE.get(p, 0) > seq:
                    return        # a NEWER save already landed: don't clobber
                _ASYNC_DONE[p] = seq
                os.replace(tmp, p)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    t = threading.Thread(target=_write, daemon=True)
    t.start()
    _ASYNC_TASKS.append(t)


def clear_async_save_task_queue():
    """Join every outstanding async_save writer (framework/io.py parity)."""
    while _ASYNC_TASKS:
        _ASYNC_TASKS.pop().join()
