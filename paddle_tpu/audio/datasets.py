"""paddle.audio.datasets — synthetic stand-ins (zero-egress environment)."""
from __future__ import annotations

import numpy as np

from ..io import Dataset


class TESS(Dataset):
    def __init__(self, mode="train", n_fold=5, split=1, feat_type="raw",
                 archive=None, **kw):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.n = 64
        self.waves = [rng.randn(16000).astype(np.float32) for _ in range(self.n)]
        self.labels = rng.randint(0, 7, (self.n,))

    def __getitem__(self, idx):
        return self.waves[idx], int(self.labels[idx])

    def __len__(self):
        return self.n


class ESC50(TESS):
    def __init__(self, mode="train", split=1, feat_type="raw", **kw):
        super().__init__(mode)
        rng = np.random.RandomState(2)
        self.labels = rng.randint(0, 50, (self.n,))
