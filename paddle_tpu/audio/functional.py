"""paddle.audio.functional: windows, mel scales, spectrogram features."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


def get_window(window, win_length, fftbins=True, dtype="float32"):
    n = win_length
    if window in ("hann", "hanning"):
        w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    elif window == "hamming":
        w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    elif window == "blackman":
        w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    else:
        w = np.ones(n)
    return Tensor(jnp.asarray(w, jnp.float32))


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    # clamp before the log: np.where evaluates BOTH branches, so hz=0
    # would emit a divide-by-zero warning from the (unselected) log arm
    f_log = np.maximum(f, min_log_hz)
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(f_log / min_log_hz) / logstep,
                    mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = np.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    f_max = f_max or sr / 2.0
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2.0, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_bins))
    for m in range(n_mels):
        lo, c, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(c - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - c, 1e-10)
        fb[m] = np.maximum(0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb, jnp.float32))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    def _p2db(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return apply_op(_p2db, spect, _op_name="power_to_db")


def create_dct(n_mfcc, n_mels, norm="ortho"):
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(2.0 / n_mels)
    return Tensor(jnp.asarray(dct.T, jnp.float32))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """parity: audio.functional.fft_frequencies."""
    import numpy as _np

    import paddle_tpu as paddle

    return paddle.to_tensor(
        _np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """parity: audio.functional.mel_frequencies."""
    import numpy as _np

    import paddle_tpu as paddle

    lo, hi = hz_to_mel(f_min, htk), hz_to_mel(f_max, htk)
    mels = _np.linspace(lo, hi, n_mels)
    return paddle.to_tensor(
        _np.asarray([mel_to_hz(m, htk) for m in mels]).astype(dtype))
