"""paddle.audio — spectral features (parity: python/paddle/audio)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply_op
from . import functional  # noqa: F401
