"""paddle.audio — spectral features (parity: python/paddle/audio)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import apply_op
from . import functional  # noqa: F401

from . import functional as features  # noqa: F401  (feature extractors live here)
from . import datasets  # noqa: F401
from . import backends  # noqa: F401
from .backends import info, load, save  # noqa: F401
