"""paddle.audio.features (parity: audio/features/layers.py) — feature
extraction Layers over the functional fbank/dct/window helpers."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...nn.layer.layers import Layer
from .. import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = jnp.asarray(
            AF.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        import paddle_tpu as paddle

        spec = paddle.signal.stft(
            x, n_fft=self.n_fft, hop_length=self.hop_length,
            win_length=self.win_length,
            window=paddle.to_tensor(np.asarray(self.window)),
            center=self.center, pad_mode=self.pad_mode)
        mag = spec.abs()
        return mag ** self.power if self.power != 1.0 else mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode,
                                       dtype)
        self.fbank = jnp.asarray(AF.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm))

    def forward(self, x):
        from ...core.dispatch import apply_op

        spec = self.spectrogram(x)

        def _mel(s):
            return jnp.einsum("mf,...ft->...mt", self.fbank,
                              s.astype(jnp.float32)).astype(s.dtype)

        return apply_op(_mel, spec, _op_name="mel_spectrogram")


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), ref_value=self.ref_value,
                              amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db, dtype)
        self.dct = jnp.asarray(AF.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        from ...core.dispatch import apply_op

        lm = self.logmel(x)

        def _dct(s):
            return jnp.einsum("nm,...mt->...nt", self.dct.T,
                              s.astype(jnp.float32)).astype(s.dtype)

        return apply_op(_dct, lm, _op_name="mfcc")
