"""paddle.audio.backends — wav IO without external deps."""
from __future__ import annotations

import wave

import numpy as np


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample


def info(filepath):
    with wave.open(str(filepath), "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(), w.getnchannels(),
                         w.getsampwidth() * 8)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    with wave.open(str(filepath), "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        w.setpos(frame_offset)
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = w.readframes(count)
    data = np.frombuffer(raw, dtype=np.int16).reshape(-1, ch)
    if normalize:
        data = data.astype(np.float32) / 32768.0
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         bits_per_sample=16):
    arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
    if channels_first:
        arr = arr.T
    if arr.dtype != np.int16:
        arr = np.clip(arr * 32768.0, -32768, 32767).astype(np.int16)
    with wave.open(str(filepath), "wb") as w:
        w.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes(arr.tobytes())


def list_available_backends():
    return ["wave"]


def get_current_backend():
    return "wave"


def set_backend(backend_name):
    pass
