"""paddle.sparse — COO/CSR tensors over jax.experimental.sparse (BCOO).

Parity target: python/paddle/sparse. XLA on TPU has no native sparse kernels;
BCOO lowers to gather/scatter + dense matmul segments, matching the
capability (not the kernel strategy) of phi/kernels/sparse.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op


class SparseCooTensor(Tensor):
    """COO tensor whose DENSE view is lazy: construction stores only
    indices+values (O(nnz) memory); ``_data`` densifies on first access by
    a dense-only consumer. Sparse-native paths (value-wise ops, rulebook
    convs, bcoo matmul) never trigger it — peak memory scales with nnz,
    not volume (the reference's whole sparse-kernel point,
    phi/kernels/sparse/)."""

    def __init__(self, indices, values, shape, coalesced=False):
        from jax.experimental import sparse as jsparse

        ind = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
        val = values._data if isinstance(values, Tensor) else jnp.asarray(values)
        self._bcoo = jsparse.BCOO((val, ind.T), shape=tuple(shape))
        super().__init__(None, stop_gradient=True)
        self._indices = Tensor(ind)
        # keep the caller's Tensor so the autograd graph reaches the values
        self._values = values if isinstance(values, Tensor) else Tensor(val)

    # -- lazy dense payload ------------------------------------------------
    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        self._dense_cache = v

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def ndim(self):
        return len(self._bcoo.shape)

    @property
    def size(self):
        import numpy as _np

        return int(_np.prod(self._bcoo.shape)) if self._bcoo.shape else 1

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def nnz(self):
        return int(self._values._data.shape[0])

    def to_dense(self):
        return Tensor(self._data)

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        ind = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
        shape = tuple(int(ind[i].max()) + 1 for i in range(ind.shape[0]))
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    crows_np = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(Tensor(jnp.asarray(indices)), values, shape)


def matmul(x, y, name=None):
    """Sparse x dense matmul via BCOO dot_general — stays sparse on the
    lhs (no densify), lowering to gather+segment-sum on TPU."""
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        def _smm(values, dense):
            from jax.experimental import sparse as jsparse

            m = jsparse.BCOO((values, x._bcoo.indices), shape=x._bcoo.shape)
            return jsparse.bcoo_dot_general(
                m, dense, dimension_numbers=(((m.ndim - 1,), (0,)), ((), ())))

        return apply_op(_smm, x.values(), y, _op_name="sparse_matmul")
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    from ..ops.linalg import matmul as _mm

    return _mm(xd, yd)


def masked_matmul(x, y, mask, name=None):
    """Dense x dense -> sparse SDDMM: computes ONLY `mask`'s nonzero
    positions — rows of x and columns of y are gathered at the mask's
    (row, col) pairs and dotted, so work and intermediates are
    O(nnz * K), never the [M, N] dense product (parity:
    phi/kernels/sparse/gpu/matmul_kernel.cu SDDMM; the O(nnz) contract
    is asserted on the jaxpr in tests/test_domains.py)."""
    if isinstance(mask, SparseCooTensor):
        ind = mask.indices()
        nd = len(mask.shape)

        def _sddmm(xd, yd, idx):
            parts = [idx[i] for i in range(nd)]
            batch, r, c = parts[:-2], parts[-2], parts[-1]
            # flatten leading batch dims so both gathers have an adjacent
            # (batch, coord) advanced-index pair -> uniform [nnz, K]
            xb = xd.reshape((-1,) + tuple(xd.shape[-2:]))
            yb = yd.reshape((-1,) + tuple(yd.shape[-2:]))
            bkey = jnp.zeros_like(r)
            for d, bi in enumerate(batch):
                bkey = bkey * xd.shape[d] + bi
            xr = xb[bkey, r, :]                           # [nnz, K]
            yc = jnp.swapaxes(yb, -1, -2)[bkey, c, :]     # [nnz, K]
            return jnp.einsum("nk,nk->n", xr, yc)

        vals = apply_op(_sddmm, x, y, ind, _op_name="masked_matmul")
        return sparse_coo_tensor(ind, vals, tuple(mask.shape))
    return matmul(x, y) * mask


def _valuewise(name, jfn):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            vals = apply_op(jfn, x.values(), _op_name=f"sparse_{name}")
            return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


sin = _valuewise("sin", jnp.sin)
tan = _valuewise("tan", jnp.tan)
asin = _valuewise("asin", jnp.arcsin)
atan = _valuewise("atan", jnp.arctan)
sinh = _valuewise("sinh", jnp.sinh)
tanh = _valuewise("tanh", jnp.tanh)
asinh = _valuewise("asinh", jnp.arcsinh)
atanh = _valuewise("atanh", jnp.arctanh)
sqrt = _valuewise("sqrt", jnp.sqrt)
square = _valuewise("square", jnp.square)
abs = _valuewise("abs", jnp.abs)
expm1 = _valuewise("expm1", jnp.expm1)
log1p = _valuewise("log1p", jnp.log1p)
neg = _valuewise("neg", lambda a: -a)


def pow(x, factor, name=None):
    if isinstance(x, SparseCooTensor):
        vals = apply_op(lambda v: jnp.power(v, factor), x.values(),
                        _op_name="sparse_pow")
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
    return apply_op(lambda v: jnp.power(v, factor), x, _op_name="pow")


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x, y, name=None):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return xd + yd


def relu(x, name=None):
    from ..nn.functional.activation import relu as _relu

    if isinstance(x, SparseCooTensor):
        return sparse_coo_tensor(x.indices(), _relu(x.values()), tuple(x.shape))
    return _relu(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if isinstance(x, SparseCooTensor):
        vals = x.values()
        if value_dtype is not None:
            vals = vals.astype(value_dtype)
        ind = x.indices()
        if index_dtype is not None:
            ind = ind.astype(index_dtype)
        return sparse_coo_tensor(ind, vals, tuple(x.shape))
    return x.astype(value_dtype) if value_dtype else x


deg2rad = _valuewise("deg2rad", jnp.deg2rad)
rad2deg = _valuewise("rad2deg", jnp.rad2deg)
isnan = _valuewise("isnan", jnp.isnan)


def mv(x, vec, name=None):
    return matmul(x, vec)


def mask_as(x, mask, name=None):
    """Keep x's values at mask's nonzero coordinate pattern."""
    if isinstance(mask, SparseCooTensor):
        ind = mask.indices()
        def _take(xd, idx):
            return xd[tuple(idx)]
        vals = apply_op(_take, x, ind, _op_name="mask_take")
        return sparse_coo_tensor(ind, vals, tuple(x.shape))
    return x * mask


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return input * beta + matmul(x, y) * alpha


def _ew(name, jfn):
    def op(x, y, name=None):
        xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
        yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
        out = apply_op(jfn, xd, yd, _op_name=name)
        if isinstance(x, SparseCooTensor):
            return to_sparse_coo_auto(out)
        return out

    op.__name__ = name
    return op


subtract = _ew("subtract", lambda a, b: a - b)
multiply = _ew("multiply", lambda a, b: a * b)
divide = _ew("divide", lambda a, b: a / b)


def to_sparse_coo_auto(dense):
    arr = np.asarray(dense.numpy())
    idx = np.stack(np.nonzero(arr))
    return SparseCooTensor(Tensor(jnp.asarray(idx)),
                           Tensor(jnp.asarray(arr[tuple(idx)])),
                           arr.shape)


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        ind = np.asarray(x.indices().numpy())[list(perm)]
        shape = tuple(np.asarray(x.shape)[list(perm)])
        return sparse_coo_tensor(ind, x.values(), shape)
    from ..ops.manipulation import transpose as _t

    return _t(x, perm)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    return apply_op(lambda a: jnp.sum(a, axis=axis, keepdims=keepdim), xd,
                    _op_name="sparse_sum")


def coalesce(x, name=None):
    """Merge duplicate coordinates (sums values)."""
    ind = np.asarray(x.indices().numpy())
    dense = np.asarray(x.to_dense().numpy())
    idx = np.stack(np.nonzero(dense))
    return SparseCooTensor(Tensor(jnp.asarray(idx)),
                           Tensor(jnp.asarray(dense[tuple(idx)])),
                           tuple(x.shape))


def reshape(x, shape, name=None):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    out = apply_op(lambda a: a.reshape(shape), xd, _op_name="sparse_reshape")
    if isinstance(x, SparseCooTensor):
        return to_sparse_coo_auto(out)
    return out


def slice(x, axes, starts, ends, name=None):
    import builtins

    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x

    def _sl(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, st, en in zip(axes, starts, ends):
            idx[ax] = builtins.slice(st, en)
        return a[tuple(idx)]

    out = apply_op(_sl, xd, _op_name="sparse_slice")
    if isinstance(x, SparseCooTensor):
        return to_sparse_coo_auto(out)
    return out


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..linalg_ns import pca_lowrank as _pca

    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    return _pca(xd, q=q, center=center, niter=niter)


from . import nn  # noqa: E402,F401
