"""paddle.sparse — COO/CSR tensors over jax.experimental.sparse (BCOO).

Parity target: python/paddle/sparse. XLA on TPU has no native sparse kernels;
BCOO lowers to gather/scatter + dense matmul segments, matching the
capability (not the kernel strategy) of phi/kernels/sparse.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op


class SparseCooTensor(Tensor):
    def __init__(self, indices, values, shape, coalesced=False):
        from jax.experimental import sparse as jsparse

        ind = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
        val = values._data if isinstance(values, Tensor) else jnp.asarray(values)
        self._bcoo = jsparse.BCOO((val, ind.T), shape=tuple(shape))
        super().__init__(self._bcoo.todense(), stop_gradient=True)
        self._indices = Tensor(ind)
        self._values = Tensor(val)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def is_sparse_coo(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None, stop_gradient=True):
    if shape is None:
        ind = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
        shape = tuple(int(ind[i].max()) + 1 for i in range(ind.shape[0]))
    return SparseCooTensor(indices, values, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None, stop_gradient=True):
    crows_np = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return SparseCooTensor(Tensor(jnp.asarray(indices)), values, shape)


def matmul(x, y, name=None):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    from ..ops.linalg import matmul as _mm

    return _mm(xd, yd)


def add(x, y, name=None):
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return xd + yd


def relu(x, name=None):
    from ..nn.functional.activation import relu as _relu

    if isinstance(x, SparseCooTensor):
        return sparse_coo_tensor(x.indices(), _relu(x.values()), tuple(x.shape))
    return _relu(x)
