"""paddle.sparse.nn.functional: value-wise activations on sparse tensors."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from .. import SparseCooTensor, sparse_coo_tensor


def _valuewise(name, jfn):
    def op(x, *args, **kwargs):
        if isinstance(x, SparseCooTensor):
            vals = apply_op(jfn, x.values(), _op_name=name)
            return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


relu = _valuewise("relu", lambda a: jnp.maximum(a, 0))
relu6 = _valuewise("relu6", lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    if isinstance(x, SparseCooTensor):
        vals = apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a),
                        x.values(), _op_name="leaky_relu")
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
    return apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a), x,
                    _op_name="leaky_relu")


def softmax(x, axis=-1):
    """Sparse softmax over the last dense axis (on the dense view, zeros
    excluded per-row via masking)."""
    from ...core.dispatch import apply_op as _ao

    if isinstance(x, SparseCooTensor):
        dense = x.to_dense()

        def _sm(a):
            mask = a != 0
            lg = jnp.where(mask, a, -1e30)
            out = jax.nn.softmax(lg, axis=axis)
            return jnp.where(mask, out, 0.0)

        out = _ao(_sm, dense, _op_name="sparse_softmax")
        from .. import to_sparse_coo_auto

        return to_sparse_coo_auto(out)
    return _ao(lambda a: jax.nn.softmax(a, axis=axis), x, _op_name="softmax")


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (parity: sparse/nn/functional/transformer.py)."""
    from ...nn.functional.flash_attention import _xla_sdpa

    mask_dense = sparse_mask.to_dense() if isinstance(
        sparse_mask, SparseCooTensor) else sparse_mask

    def _attn(q, k, v, m):
        lg_mask = jnp.where(m != 0, 0.0, -1e30)
        qh = jnp.swapaxes(q, 1, 2) if q.ndim == 4 else q
        return _xla_sdpa(q, k, v, mask=lg_mask)

    return apply_op(_attn, query, key, value, mask_dense,
                    _op_name="sparse_attention")
