"""paddle.sparse.nn.functional: value-wise activations on sparse tensors."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply_op
from .. import SparseCooTensor, sparse_coo_tensor


def _valuewise(name, jfn):
    def op(x, *args, **kwargs):
        if isinstance(x, SparseCooTensor):
            vals = apply_op(jfn, x.values(), _op_name=name)
            return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


relu = _valuewise("relu", lambda a: jnp.maximum(a, 0))
relu6 = _valuewise("relu6", lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    if isinstance(x, SparseCooTensor):
        vals = apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a),
                        x.values(), _op_name="leaky_relu")
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
    return apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a), x,
                    _op_name="leaky_relu")


def _row_keys(indices_np, shape):
    """Linearised leading-dims row id per stored element + row count."""
    nd = len(shape)
    rows = np.zeros(indices_np.shape[1], np.int64)
    for d in range(nd - 1):
        rows = rows * int(shape[d]) + indices_np[d]
    nrows = 1
    for d in range(nd - 1):
        nrows *= int(shape[d])
    return rows, nrows


def softmax(x, axis=-1):
    """Sparse softmax over the last axis, computed directly on the STORED
    values with per-row segment max/sum — O(nnz), the dense view is never
    materialised (parity: phi/kernels/sparse/gpu/softmax_kernel.cu; same
    semantics — the softmax runs over the stored elements of each row)."""
    from ...core.dispatch import apply_op as _ao

    if isinstance(x, SparseCooTensor):
        if axis not in (-1, len(x.shape) - 1):
            raise ValueError("sparse softmax supports the last axis only "
                             "(reference kernel contract)")
        ind_np = np.asarray(x.indices().numpy())
        rows, nrows = _row_keys(ind_np, x.shape)
        rows_j = jnp.asarray(rows)

        def _sm(vals):
            m = jax.ops.segment_max(vals, rows_j, num_segments=nrows)
            e = jnp.exp(vals - m[rows_j])
            s = jax.ops.segment_sum(e, rows_j, num_segments=nrows)
            return e / s[rows_j]

        vals = _ao(_sm, x.values(), _op_name="sparse_softmax")
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
    return _ao(lambda a: jax.nn.softmax(a, axis=axis), x, _op_name="softmax")


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention composed from the O(nnz) pieces: SDDMM for
    the masked q.k^T scores, per-row segment softmax over the stored
    scores, and a segment-sum spmm against v — the [S, S] score matrix
    never materialises (parity:
    phi/kernels/sparse/gpu/fused_attention_kernel.cu; q/k/v are
    [B, H, S, D], sparse_mask is [B*H, S, S] COO as in the reference).

    key_padding_mask [B, S] / attn_mask [S, S] (additive, -inf style)
    are applied to the gathered scores before the softmax."""
    if not isinstance(sparse_mask, SparseCooTensor):
        raise ValueError("sparse_mask must be a SparseCooTensor")
    ind = sparse_mask.indices()
    ind_np = np.asarray(ind.numpy())
    rows, nrows = _row_keys(ind_np, sparse_mask.shape)
    rows_j = jnp.asarray(rows)

    def _attn(q, k, v, idx, kp, am):
        B, H, S, D = q.shape
        qf = q.reshape(B * H, S, D)
        kf = k.reshape(B * H, S, D)
        vf = v.reshape(B * H, S, D)
        g, i, j = idx[0], idx[1], idx[2]
        scores = jnp.einsum(
            "nd,nd->n", qf[g, i, :], kf[g, j, :]) / np.sqrt(D)
        if kp is not None:
            scores = scores + kp[g // H, j]
        if am is not None:
            scores = scores + am[i, j]
        # clamp the per-row max so fully-masked rows (-inf everywhere)
        # yield 0-weight rows instead of exp(-inf - -inf) = NaN
        m = jax.ops.segment_max(scores, rows_j, num_segments=nrows)
        m = jnp.maximum(m, -1e30)
        e = jnp.exp(scores - m[rows_j])
        s = jax.ops.segment_sum(e, rows_j, num_segments=nrows)
        p = e / jnp.maximum(s[rows_j], 1e-30)
        out = jax.ops.segment_sum(p[:, None] * vf[g, j, :],
                                  g * S + i, num_segments=B * H * S)
        return out.reshape(B, H, S, D)

    return apply_op(_attn, query, key, value, ind, key_padding_mask,
                    attn_mask, _op_name="sparse_attention")


# -- sparse conv functionals (parity: sparse/nn/functional/conv.py) ---------
def _build_rulebook(indices_np, spatial, ksize, stride, padding, dilation,
                    subm, batch_size=1):
    """Host-built rulebook (reference: the GPU rulebook construction in
    phi/kernels/sparse/gpu/conv_kernel.cu): per kernel offset, the
    (input_row, output_row) gather/scatter pairs.

    indices_np: [1+nd, nnz] (batch + nd spatial coords). Returns
    (out_indices [1+nd, n_out], [(in_rows, out_rows)] per offset).
    Vectorised numpy: coordinate hashing = linearisation + searchsorted —
    no dense volume is ever materialised.
    """
    import itertools

    nd = len(ksize)
    coords = indices_np.T.astype(np.int64)              # [nnz, 1+nd]
    dims = [int(batch_size)] + list(spatial)

    def lin(c):                                          # [m, 1+nd] -> [m]
        out = c[:, 0]
        for d in range(nd):
            out = out * spatial[d] + c[:, d + 1]
        return out

    in_lin = lin(coords)
    order = np.argsort(in_lin)
    in_sorted = in_lin[order]

    def lookup(cand_coords, valid):
        cl = lin(np.where(valid[:, None], cand_coords, 0))
        pos = np.searchsorted(in_sorted, cl)
        pos = np.clip(pos, 0, len(in_sorted) - 1)
        hit = valid & (in_sorted[pos] == cl) if len(in_sorted) else valid & False
        return order[pos], hit

    offsets = list(itertools.product(*[range(k) for k in ksize]))
    center = [(k - 1) // 2 for k in ksize]

    if subm:
        out_coords = coords
        n_out = len(coords)
        out_row_of = np.arange(n_out)
        rulebook = []
        for off in offsets:
            # out[p] += w[off] * in[p + (off - center)*dil]
            delta = np.array([0] + [(off[d] - center[d]) * dilation[d]
                                    for d in range(nd)], np.int64)
            cand = out_coords + delta
            valid = np.ones(len(cand), bool)
            for d in range(nd):
                valid &= (cand[:, d + 1] >= 0) & (cand[:, d + 1] < spatial[d])
            in_rows, hit = lookup(cand, valid)
            rulebook.append((in_rows[hit], out_row_of[hit]))
        return indices_np, rulebook, [int(d) for d in dims]

    # full conv: out[p] = sum_off w[off] * in[p*stride - pad + off*dil]
    out_spatial = [
        (spatial[d] + 2 * padding[d] - dilation[d] * (ksize[d] - 1) - 1)
        // stride[d] + 1 for d in range(nd)]
    pair_in, pair_out_coord, pair_off = [], [], []
    for oi, off in enumerate(offsets):
        # in coord q maps to out p = (q + pad - off*dil) / stride
        num = coords[:, 1:] + np.array(
            [padding[d] - off[d] * dilation[d] for d in range(nd)], np.int64)
        ok = np.ones(len(coords), bool)
        for d in range(nd):
            ok &= (num[:, d] % stride[d] == 0)
        p = num // np.array(stride, np.int64)
        for d in range(nd):
            ok &= (p[:, d] >= 0) & (p[:, d] < out_spatial[d])
        oc = np.concatenate([coords[:, :1], p], axis=1)
        pair_in.append(np.arange(len(coords))[ok])
        pair_out_coord.append(oc[ok])
        pair_off.append(np.full(ok.sum(), oi))
    all_out = (np.concatenate(pair_out_coord) if pair_out_coord
               else np.zeros((0, 1 + nd), np.int64))

    def lin_out(c):
        out = c[:, 0]
        for d in range(nd):
            out = out * out_spatial[d] + c[:, d + 1]
        return out

    uniq_lin, inverse = np.unique(lin_out(all_out), return_inverse=True)
    # reconstruct unique out coords from the first occurrence
    first = np.zeros(len(uniq_lin), np.int64)
    first[inverse] = np.arange(len(all_out))
    out_coords = all_out[first]
    rulebook, base = [], 0
    for oi in range(len(offsets)):
        n = len(pair_in[oi])
        rulebook.append((pair_in[oi], inverse[base:base + n]))
        base += n
    out_dims = [int(dims[0])] + [int(s) for s in out_spatial]
    return out_coords.T, rulebook, out_dims


def _rulebook_conv_values(values, w_flat, bias, rulebook, n_out):
    """Pure gather-matmul-scatter compute: values [nnz, Cin], w_flat
    [K, Cin, Cout]. Peak memory O(nnz * C), never O(volume)."""
    cout = w_flat.shape[-1]
    out = jnp.zeros((n_out, cout), values.dtype)
    for k, (ii, oi) in enumerate(rulebook):
        if len(ii) == 0:
            continue
        contrib = values[jnp.asarray(ii)] @ w_flat[k]
        out = out.at[jnp.asarray(oi)].add(contrib)
    if bias is not None:
        out = out + bias
    return out


def _conv_nd_rulebook(x, weight, bias, stride, padding, dilation, subm, nd):
    from .. import sparse_coo_tensor

    if subm and any(s != 1 for s in stride):
        raise ValueError(
            "submanifold conv preserves the input sparsity pattern; "
            f"stride={tuple(stride)} is not representable (use the "
            "non-subm conv for strided downsampling)")

    indices_np = np.asarray(x.indices().numpy())
    spatial = [int(s) for s in x.shape[1:1 + nd]]

    # coalesce duplicate sites first (sparse_coo_tensor never coalesces;
    # the dense path summed duplicates via todense, so must we)
    lin = indices_np[0].astype(np.int64)
    for d in range(nd):
        lin = lin * spatial[d] + indices_np[1 + d]
    uniq, first_idx, inverse = np.unique(lin, return_index=True,
                                         return_inverse=True)
    coalesced = len(uniq) != indices_np.shape[1]
    if coalesced:
        indices_np = indices_np[:, first_idx]

    out_idx, rulebook, out_dims = _build_rulebook(
        indices_np, spatial, [int(weight.shape[d]) for d in range(nd)],
        list(stride), list(padding), list(dilation), subm,
        batch_size=int(x.shape[0]))
    n_out = out_idx.shape[1]
    cout = int(weight.shape[-1])
    inv = jnp.asarray(inverse)
    n_uniq = len(uniq)

    def _compute(vals, w, b):
        if coalesced:
            vals = jnp.zeros((n_uniq,) + tuple(vals.shape[1:]),
                             vals.dtype).at[inv].add(vals)
        w_flat = w.reshape((-1,) + tuple(w.shape[-2:]))  # [K, Cin, Cout]
        return _rulebook_conv_values(vals, w_flat, b, rulebook, n_out)

    out_vals = apply_op(_compute, x.values(), weight, bias,
                        _op_name=f"subm_conv{nd}d" if subm
                        else f"sparse_conv{nd}d")
    shape = tuple(out_dims) + (cout,)
    return sparse_coo_tensor(jnp.asarray(out_idx), out_vals, shape)


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, subm, nd):
    """Sparse-native gather-matmul-scatter conv over a host-built rulebook
    (COO inputs, peak memory O(nnz)); dense inputs / grouped convs take
    the lax conv path."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply_op
    from .. import SparseCooTensor, sparse_coo_tensor, to_sparse_coo_auto

    if nd == 3:
        dn = ("NDHWC", "DHWIO", "NDHWC")
    else:
        dn = ("NHWC", "HWIO", "NHWC")
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)

    if isinstance(x, SparseCooTensor) and groups == 1:
        return _conv_nd_rulebook(x, weight, bias, stride, padding,
                                 dilation, subm, nd)

    dense = x.to_dense() if isinstance(x, SparseCooTensor) else x

    def _c(a, w, b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride,
            padding=[(p, p) for p in padding],
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups)
        if b is not None:
            out = out + b
        return out

    out = apply_op(_c, dense, weight, bias, _op_name=f"sparse_conv{nd}d")
    if subm and isinstance(x, SparseCooTensor):
        # submanifold: zero everywhere the INPUT had no active SITE
        # (site = batch+spatial position, any channel) — all output
        # channels survive at active sites
        site_mask = apply_op(
            lambda a: (a != 0).any(-1, keepdims=True), dense,
            _op_name="subm_site_mask")
        out = apply_op(lambda o, m: o * m.astype(o.dtype), out, site_mask,
                       _op_name="subm_mask")
    return to_sparse_coo_auto(out)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=False, nd=3)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=True, nd=3)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=False, nd=2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=True, nd=2)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply_op
    from .. import SparseCooTensor, to_sparse_coo_auto

    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dense = x.to_dense() if isinstance(x, SparseCooTensor) else x

    def _mp(a):
        return jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max,
            window_dimensions=(1,) + ks + (1,),
            window_strides=(1,) + st + (1,),
            padding=((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),))

    out = apply_op(_mp, dense, _op_name="sparse_max_pool3d")
    return to_sparse_coo_auto(out)
