"""paddle.sparse.nn.functional: value-wise activations on sparse tensors."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from .. import SparseCooTensor, sparse_coo_tensor


def _valuewise(name, jfn):
    def op(x, *args, **kwargs):
        if isinstance(x, SparseCooTensor):
            vals = apply_op(jfn, x.values(), _op_name=name)
            return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
        return apply_op(jfn, x, _op_name=name)

    op.__name__ = name
    return op


relu = _valuewise("relu", lambda a: jnp.maximum(a, 0))
relu6 = _valuewise("relu6", lambda a: jnp.clip(a, 0, 6))


def leaky_relu(x, negative_slope=0.01):
    if isinstance(x, SparseCooTensor):
        vals = apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a),
                        x.values(), _op_name="leaky_relu")
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))
    return apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a), x,
                    _op_name="leaky_relu")


def softmax(x, axis=-1):
    """Sparse softmax over the last dense axis (on the dense view, zeros
    excluded per-row via masking)."""
    from ...core.dispatch import apply_op as _ao

    if isinstance(x, SparseCooTensor):
        dense = x.to_dense()

        def _sm(a):
            mask = a != 0
            lg = jnp.where(mask, a, -1e30)
            out = jax.nn.softmax(lg, axis=axis)
            return jnp.where(mask, out, 0.0)

        out = _ao(_sm, dense, _op_name="sparse_softmax")
        from .. import to_sparse_coo_auto

        return to_sparse_coo_auto(out)
    return _ao(lambda a: jax.nn.softmax(a, axis=axis), x, _op_name="softmax")


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (parity: sparse/nn/functional/transformer.py)."""
    from ...nn.functional.flash_attention import _xla_sdpa

    mask_dense = sparse_mask.to_dense() if isinstance(
        sparse_mask, SparseCooTensor) else sparse_mask

    def _attn(q, k, v, m):
        lg_mask = jnp.where(m != 0, 0.0, -1e30)
        qh = jnp.swapaxes(q, 1, 2) if q.ndim == 4 else q
        return _xla_sdpa(q, k, v, mask=lg_mask)

    return apply_op(_attn, query, key, value, mask_dense,
                    _op_name="sparse_attention")


# -- sparse conv functionals (parity: sparse/nn/functional/conv.py) ---------
def _conv_nd(x, weight, bias, stride, padding, dilation, groups, subm, nd):
    """Densify -> lax conv (channel-last) -> resparsify; subm keeps the
    input's sparsity pattern (submanifold semantics)."""
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply_op
    from .. import SparseCooTensor, sparse_coo_tensor, to_sparse_coo_auto

    if nd == 3:
        dn = ("NDHWC", "DHWIO", "NDHWC")
    else:
        dn = ("NHWC", "HWIO", "NHWC")
    stride = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    padding = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    dilation = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)

    dense = x.to_dense() if isinstance(x, SparseCooTensor) else x

    def _c(a, w, b):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride,
            padding=[(p, p) for p in padding],
            rhs_dilation=dilation,
            dimension_numbers=dn,
            feature_group_count=groups)
        if b is not None:
            out = out + b
        return out

    out = apply_op(_c, dense, weight, bias, _op_name=f"sparse_conv{nd}d")
    if subm and isinstance(x, SparseCooTensor):
        # submanifold: zero everywhere the INPUT had no active SITE
        # (site = batch+spatial position, any channel) — all output
        # channels survive at active sites
        site_mask = apply_op(
            lambda a: (a != 0).any(-1, keepdims=True), dense,
            _op_name="subm_site_mask")
        out = apply_op(lambda o, m: o * m.astype(o.dtype), out, site_mask,
                       _op_name="subm_mask")
    return to_sparse_coo_auto(out)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=False, nd=3)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=True, nd=3)


def subm_conv3d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NDHWC", key=None, name=None):
    return subm_conv3d(x, weight, bias, stride, padding, dilation, groups)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=False, nd=2)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups,
                    subm=True, nd=2)


def subm_conv2d_igemm(x, weight, bias=None, stride=1, padding=0, dilation=1,
                      groups=1, data_format="NHWC", key=None, name=None):
    return subm_conv2d(x, weight, bias, stride, padding, dilation, groups)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    import jax
    import jax.numpy as jnp

    from ...core.dispatch import apply_op
    from .. import SparseCooTensor, to_sparse_coo_auto

    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dense = x.to_dense() if isinstance(x, SparseCooTensor) else x

    def _mp(a):
        return jax.lax.reduce_window(
            a, -jnp.inf, jax.lax.max,
            window_dimensions=(1,) + ks + (1,),
            window_strides=(1,) + st + (1,),
            padding=((0, 0),) + tuple((p, p) for p in pd) + ((0, 0),))

    out = apply_op(_mp, dense, _op_name="sparse_max_pool3d")
    return to_sparse_coo_auto(out)
