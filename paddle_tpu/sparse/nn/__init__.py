"""paddle.sparse.nn (parity: python/paddle/sparse/nn): layers operating on
SparseCooTensor activations. TPU form: compute on values (elementwise) or
densified neighborhoods (conv) — XLA has no sparse conv kernels, matching
capability not kernel strategy."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn.layer.layers import Layer
from .. import SparseCooTensor, sparse_coo_tensor
from . import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return functional.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return functional.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return functional.softmax(x, self.axis)


class BatchNorm(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn.layer.norm import BatchNorm1D

        self.inner = BatchNorm1D(num_features, momentum=momentum,
                                 epsilon=epsilon)

    def forward(self, x):
        vals = self.inner(x.values())
        return sparse_coo_tensor(x.indices(), vals, tuple(x.shape))


class SyncBatchNorm(BatchNorm):
    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class _SparseConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__()
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.subm = subm
        self.stride = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
        self.padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        self.weight = self.create_parameter(
            list(ks) + [in_channels, out_channels])
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], is_bias=True))

    def forward(self, x):
        # densify -> conv3d (NDHWC) -> resparsify
        from ...core.dispatch import apply_op

        dense = x.to_dense()

        def _c(a, w, b):
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=self.stride,
                padding=[(p, p) for p in self.padding],
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
            if b is not None:
                out = out + b
            return out

        out = apply_op(_c, dense, self.weight, self.bias,
                       _op_name="sparse_conv3d")
        from .. import to_sparse_coo_auto

        return to_sparse_coo_auto(out)


class Conv3D(_SparseConvNd):
    pass


class SubmConv3D(_SparseConvNd):
    def __init__(self, *args, **kwargs):
        kwargs["subm"] = True
        super().__init__(*args, **kwargs)


class Conv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, key=None,
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__()
        from ...nn.layer.conv import Conv2D as DenseConv2D

        self.inner = DenseConv2D(in_channels, out_channels, kernel_size,
                                 stride, padding, dilation, groups,
                                 bias_attr=bias_attr)

    def forward(self, x):
        import paddle_tpu as paddle

        dense = x.to_dense()
        nchw = paddle.transpose(dense, [0, 3, 1, 2])
        out = self.inner(nchw)
        out = paddle.transpose(out, [0, 2, 3, 1])
        from .. import to_sparse_coo_auto

        return to_sparse_coo_auto(out)


class SubmConv2D(Conv2D):
    pass


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = self.ks if stride is None else (
            (stride,) * 3 if isinstance(stride, int) else tuple(stride))
        self.padding = (padding,) * 3 if isinstance(padding, int) else tuple(padding)

    def forward(self, x):
        from ...core.dispatch import apply_op

        dense = x.to_dense()

        def _mp(a):
            return jax.lax.reduce_window(
                a, -jnp.inf, jax.lax.max,
                (1,) + self.ks + (1,), (1,) + self.stride + (1,),
                [(0, 0)] + [(p, p) for p in self.padding] + [(0, 0)])

        out = apply_op(_mp, dense, _op_name="sparse_maxpool3d")
        from .. import to_sparse_coo_auto

        return to_sparse_coo_auto(out)
