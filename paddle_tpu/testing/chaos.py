"""Fault-injection harness for crash-safe checkpointing.

Four fault families, matching how real training jobs die
(docs/CHECKPOINT.md "Chaos harness"):

- **Process death**: `run_until_step` launches a training subprocess and
  SIGKILLs it the moment its stdout reports a chosen step — the
  save→kill→resume cycle `tests/test_chaos_resume.py` proves safe.
- **On-disk corruption**: `truncate_file` / `corrupt_file` damage a
  committed or in-flight shard; `newest_step_file` finds the target the
  way an operator would (newest step dir, committed or not).
- **Writer faults**: `transient_write_errors` raises OSError on the
  first N write attempts (exercises retry/backoff);
  `failing_writes` raises on EVERY attempt (an async save that can never
  land must surface on `wait()`, and its step must stay uncommitted).
- **Interrupted async save**: `die_during_write` hard-exits the process
  (`os._exit`) the first time a matching file is written — the
  interpreter dies mid-save with no atexit, no cleanup, exactly like a
  preemption landing during an async flush.
- **Training anomalies**: `inject_nonfinite` / `inject_anomaly` make a
  chosen step invocation compute NaN/Inf grads (or a poisoned loss)
  INSIDE the compiled train step — the one-bad-batch /
  flaky-interconnect fault `resilience.StepGuard` exists to survive.
- **Fleet faults**: `ChaosReplica` wraps one serving replica's fleet
  surface with deterministic tick-counted fault injection — step
  latency (straggler), intermittent transient exceptions, a flapping
  replica — the seam the FleetRouter circuit breakers are proven
  against (docs/SERVING.md "Overload & degradation").
- **Wire faults**: `ChaosTransport` wraps one fleet transport link
  with deterministic send-ordinal-keyed frame faults — drop, delay,
  duplicate, corrupt (byte flip past the header), and sever-for-N-calls
  — the seam the RPC retry/idempotency machinery is proven against
  (docs/SERVING.md "Process topology"). `PartitionedLink` holds one
  link severed as a STATE (sever/heal), the network-partition seam the
  cross-host fencing machinery (fleet.hosts) is proven against.

Every injector routes through a seam its subsystem exposes
(`distributed.checkpoint._WRITE_FAULT_HOOK` for writes,
`resilience._ANOMALY_FAULT_HOOK` for step anomalies); nothing here
monkeypatches internals.
"""
from __future__ import annotations

import contextlib
import os
import re
import signal
import subprocess
import sys
import time

from ..distributed import checkpoint as _ckpt


class FaultCounter:
    """Shared mutable view of how many faults an injector has fired."""

    def __init__(self):
        self.fired = 0
        self.attempts = 0


@contextlib.contextmanager
def _install_hook(hook):
    prev = _ckpt._WRITE_FAULT_HOOK
    _ckpt._WRITE_FAULT_HOOK = hook
    try:
        yield
    finally:
        _ckpt._WRITE_FAULT_HOOK = prev


def _matches(path, match):
    return match is None or match in os.path.basename(path)


@contextlib.contextmanager
def transient_write_errors(count, match=None, errno_=None):
    """The first `count` matching write attempts raise OSError, then
    writes succeed — the shape of an NFS blip. With the default retry
    policy (3 retries, exponential backoff) a save survives count<=3."""
    ctr = FaultCounter()

    def hook(path, attempt):
        ctr.attempts += 1
        if _matches(path, match) and ctr.fired < count:
            ctr.fired += 1
            raise OSError(errno_ or 5, f"chaos: transient write error "
                                       f"#{ctr.fired} on {path}")

    with _install_hook(hook):
        yield ctr


@contextlib.contextmanager
def failing_writes(match=None):
    """EVERY matching write attempt raises OSError — storage is gone.
    The save must fail loudly (sync: raise; async: re-raise on wait())
    and must never leave a committed step behind."""
    ctr = FaultCounter()

    def hook(path, attempt):
        ctr.attempts += 1
        if _matches(path, match):
            ctr.fired += 1
            raise OSError(5, f"chaos: persistent write failure on {path}")

    with _install_hook(hook):
        yield ctr


@contextlib.contextmanager
def inject_anomaly(step, value, site="grads", count=1):
    """Inject `value` into a compiled train step's grads or loss for
    `count` consecutive step invocations starting at 1-based invocation
    `step` (per TrainStep instance). Routes through
    `resilience._ANOMALY_FAULT_HOOK` — the one seam the compiled step
    exposes, mirroring `_WRITE_FAULT_HOOK`. A finite `value` on
    site="loss" makes a loss SPIKE; nonfinite values are what
    `inject_nonfinite` wraps."""
    if site not in ("grads", "loss"):
        raise ValueError(f"site must be 'grads' or 'loss', got {site!r}")
    step, count, value = int(step), int(count), float(value)
    if value == 0.0:
        raise ValueError("value=0.0 encodes 'no injection' on the guard "
                         "operand; inject a nonzero value")
    from .. import resilience as _resilience

    ctr = FaultCounter()

    def hook(call_index):
        ctr.attempts += 1
        if step <= call_index < step + count:
            ctr.fired += 1
            return (site, value)
        return None

    with _resilience.install_anomaly_hook(hook):
        yield ctr


@contextlib.contextmanager
def inject_nonfinite(step, kind="nan", site="grads", count=1):
    """The training-anomaly fault: NaN/Inf grads (or loss) produced
    INSIDE the compiled step at step invocation `step` — the failure a
    flaky interconnect or a bad batch injects into a real run, which
    `resilience.StepGuard` must skip/rewind past
    (docs/RESILIENCE.md "Chaos proof")."""
    if kind not in ("nan", "inf"):
        raise ValueError(f"kind must be 'nan' or 'inf', got {kind!r}")
    val = float("nan") if kind == "nan" else float("inf")
    with inject_anomaly(step, val, site=site, count=count) as ctr:
        yield ctr


@contextlib.contextmanager
def die_during_write(match=None, exit_code=57):
    """Hard-exit the process (`os._exit` — no atexit, no flushing) the
    first time a matching file is about to be written: a preemption
    landing in the middle of an async save. Only meaningful in a
    subprocess driven by a test."""

    def hook(path, attempt):
        if _matches(path, match):
            os._exit(exit_code)

    with _install_hook(hook):
        yield


# ---------------------------------------------------------------------------
# fleet fault seams (docs/SERVING.md "Overload & degradation")
# ---------------------------------------------------------------------------
class ChaosReplica:
    """Wrap one engine's fleet surface with deterministic, tick-counted
    fault injection — the seam ``FleetRouter``'s circuit breakers are
    proven against (breaker open/half-open/close transitions,
    exactly-once streaming across shed/retry/replay). Everything except
    ``step()`` delegates to the wrapped engine; injected faults fire
    BEFORE the wrapped step executes, so a faulted tick is effect-free
    (the shape of a transient runtime error: the work did not happen).

    Fault families (composable, all keyed on the 1-based step ordinal so
    runs are reproducible with no wall-clock dependence):

    - ``latency``: seconds of injected ``step()`` latency — a straggler
      replica (slows the fleet tick; never fails).
    - ``fail_ticks``: explicit step ordinals that raise.
    - ``transient_every=k``: every k-th step raises — an intermittently
      flaky replica (drives breaker open -> half-open -> close).
    - ``flap=(up, down)``: ``up`` healthy steps then ``down`` failing
      steps, cycling forever — the flapping replica the overload soak
      scenario runs (breaker flap count must stay bounded).
    - ``exc_factory``: exception builder taking the step ordinal
      (default :class:`~paddle_tpu.inference.fleet.overload.
      TransientReplicaError`; pass e.g. ``RuntimeError`` to inject
      FATAL-classified faults and exercise ``max_consecutive_fatal``).
    """

    _OWN = frozenset({"_engine", "latency", "fail_ticks",
                      "transient_every", "flap", "_exc", "steps",
                      "faults"})

    def __init__(self, engine, *, latency=0.0, fail_ticks=(),
                 transient_every=None, flap=None, exc_factory=None):
        object.__setattr__(self, "_engine", engine)
        self.latency = float(latency)
        self.fail_ticks = frozenset(int(t) for t in fail_ticks)
        self.transient_every = transient_every
        self.flap = tuple(flap) if flap else None
        self._exc = exc_factory
        self.steps = 0
        self.faults = 0

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_engine"), name)

    def __setattr__(self, name, value):
        # brownout/controller writes (max_new_cap, spec_paused, ...)
        # must land on the ENGINE — only this wrapper's own fields stay
        # local, so the seam is invisible to every fleet consumer
        if name in self._OWN:
            object.__setattr__(self, name, value)
        else:
            setattr(self._engine, name, value)

    def _should_fail(self):
        t = self.steps
        if t in self.fail_ticks:
            return True
        if self.transient_every and t % int(self.transient_every) == 0:
            return True
        if self.flap:
            up, down = self.flap
            return (t - 1) % (up + down) >= up
        return False

    def step(self):
        self.steps += 1
        if self.latency:
            time.sleep(self.latency)
        if self._should_fail():
            self.faults += 1
            if self._exc is not None:
                raise self._exc(
                    f"chaos: injected fault at replica step {self.steps}")
            from ..inference.fleet.overload import TransientReplicaError

            raise TransientReplicaError(
                f"chaos: injected transient fault at replica step "
                f"{self.steps}")
        return self._engine.step()


# ---------------------------------------------------------------------------
# on-disk corruption
# ---------------------------------------------------------------------------
def truncate_file(path, keep_bytes=None, frac=0.5):
    """Cut a file short (default: to half its size) — a torn write from a
    non-atomic writer or a filesystem that lost the tail."""
    size = os.path.getsize(path)
    keep = int(size * frac) if keep_bytes is None else int(keep_bytes)
    with open(path, "rb+") as f:
        f.truncate(max(0, min(keep, size)))
    return path


def corrupt_file(path, offset=None, nbytes=4, seed=0):
    """Flip `nbytes` bytes in place (silent bit rot — size unchanged, so
    only the checksum can catch it)."""
    import random

    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    rng = random.Random(seed)
    if offset is None:
        offset = rng.randrange(max(1, size - nbytes))
    with open(path, "rb+") as f:
        f.seek(offset)
        chunk = f.read(nbytes)
        f.seek(offset)
        f.write(bytes((b ^ 0xFF) for b in chunk))
    return path


def newest_step_file(root, suffix=".distcp", committed_only=False):
    """Path of a `suffix` file in the NEWEST step directory under a
    CheckpointManager root (committed or not) — the file an operator
    would worry about after a crash."""
    from ..distributed.checkpoint.manager import CheckpointManager

    mgr = CheckpointManager(root)
    steps = mgr.all_steps(committed_only=committed_only)
    if not steps:
        raise FileNotFoundError(f"no step directories under {root}")
    step_dir = mgr.step_dir(steps[-1])
    for name in sorted(os.listdir(step_dir)):
        if name.endswith(suffix):
            return os.path.join(step_dir, name)
    raise FileNotFoundError(f"no *{suffix} file under {step_dir}")


# ---------------------------------------------------------------------------
# process death
# ---------------------------------------------------------------------------
def run_until_step(argv, kill_step, step_pattern=r"^STEP (\d+)\b",
                   sig=signal.SIGKILL, timeout=180.0, env=None, cwd=None):
    """Run `argv`; SIGKILL it as soon as a stdout line reports a step
    >= `kill_step`. Returns (killed_at_step, lines, returncode).

    killed_at_step is None if the process finished before the target
    step (the caller should assert on that)."""
    import threading

    pat = re.compile(step_pattern)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, bufsize=1, env=env, cwd=cwd)
    lines = []
    killed_at = None
    timed_out = []
    # a worker that hangs SILENTLY would block the stdout read forever;
    # the watchdog converts that into a kill + TimeoutError
    watchdog = threading.Timer(timeout,
                               lambda: (timed_out.append(True), proc.kill()))
    watchdog.start()
    try:
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
            m = pat.match(line)
            if m and killed_at is None and int(m.group(1)) >= kill_step:
                killed_at = int(m.group(1))
                proc.send_signal(sig)
                # keep draining: a graceful signal (SIGTERM) lets the
                # worker write its final save + PREEMPTED line before EOF
        proc.wait(timeout=30)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    if timed_out and killed_at is None:
        raise TimeoutError(
            f"run_until_step: no step >= {kill_step} within {timeout}s; "
            f"output tail: {lines[-10:]}")
    return killed_at, lines, proc.returncode


def run_to_completion(argv, timeout=180.0, env=None, cwd=None):
    """Run `argv` to completion; returns (lines, returncode)."""
    proc = subprocess.run(
        argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout, env=env, cwd=cwd)
    return proc.stdout.splitlines(), proc.returncode


def step_losses(lines, pattern=r"^STEP (\d+) LOSS (\S+)"):
    """{step: loss_token} parsed from worker stdout. The loss token is
    compared as an opaque string — workers print bit-exact encodings
    (float32 bytes hex), so equality here IS bit-for-bit equality."""
    pat = re.compile(pattern)
    out = {}
    for line in lines:
        m = pat.match(line)
        if m:
            out[int(m.group(1))] = m.group(2)
    return out


def subprocess_env(extra=None):
    """Minimal deterministic CPU env for training subprocesses (mirrors
    tests/conftest.py: 8 virtual devices, forced CPU backend)."""
    env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONUNBUFFERED": "1",
    }
    if "PYTHONPATH" in os.environ:
        env["PYTHONPATH"] = os.environ["PYTHONPATH"]
    if extra:
        env.update(extra)
    return env


class ChaosTransport:
    """Deterministic frame-level fault injection on ONE fleet link.

    Wraps a live :class:`~paddle_tpu.inference.fleet.transport.Transport`
    and interposes on its byte-level `_send` / `_recv_bytes` seam, so the
    retry / idempotency / CRC machinery above it is exercised for real —
    nothing here monkeypatches transport internals, and the call-level
    semantics (ids, backoff, timeouts) are the wrapped transport's own.

    Faults key on the 1-based SEND ordinal (every `_send` attempt,
    including retries, increments it), so a schedule like
    ``drop_sends={1}`` is reproducible run to run:

    - ``drop_sends``: the frame silently vanishes (client times out and
      re-sends the same call id; the server's idempotency cache keeps it
      exactly-once).
    - ``corrupt_sends``: one payload byte is flipped (server's CRC check
      rejects it loudly; never half-parsed).
    - ``duplicate_sends``: the frame is delivered twice (server replays
      the cached reply; the duplicate must not re-execute).
    - ``delay_sends`` + ``delay``: injected latency before delivery.
    - ``sever_for(n)``: the next ``n`` send attempts raise
      `TransportSevered` (a dead link that heals — the breaker's
      backoff-and-replay case).
    - ``corrupt_recvs``: flips a byte in a REPLY frame instead.
    """

    def __init__(self, inner, *, drop_sends=(), corrupt_sends=(),
                 duplicate_sends=(), delay_sends=(), delay=0.0,
                 corrupt_recvs=(), sleep=time.sleep):
        self._inner = inner
        self.drop_sends = set(drop_sends)
        self.corrupt_sends = set(corrupt_sends)
        self.duplicate_sends = set(duplicate_sends)
        self.delay_sends = set(delay_sends)
        self.delay = float(delay)
        self.corrupt_recvs = set(corrupt_recvs)
        self._sleep = sleep
        self.sends = 0
        self.recvs = 0
        self.dropped = 0
        self.corrupted = 0
        self.duplicated = 0
        self.severed_calls = 0
        self._sever_left = 0
        # the retry/call machinery runs on the wrapped transport with
        # OUR byte seam spliced in
        inner._send = self._send_faulted(inner.__class__._send, inner)
        inner._recv_bytes = self._recv_faulted(
            inner.__class__._recv_bytes, inner)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- fault schedule ------------------------------------------------------
    def sever_for(self, n):
        """Sever the link for the next ``n`` send attempts."""
        self._sever_left = int(n)

    @staticmethod
    def _flip_byte(frame):
        """Flip one PAYLOAD byte (past the header) so the CRC check —
        not the length prefix — is what catches it."""
        from paddle_tpu.inference.fleet import wire as _wire

        buf = bytearray(frame)
        pos = _wire.HEADER_SIZE if len(buf) > _wire.HEADER_SIZE else 0
        buf[pos] ^= 0xFF
        return bytes(buf)

    def _send_faulted(self, real_send, inner):
        from paddle_tpu.inference.fleet.transport import TransportSevered

        def _send(frame):
            self.sends += 1
            n = self.sends
            if self._sever_left > 0:
                self._sever_left -= 1
                self.severed_calls += 1
                raise TransportSevered(
                    f"chaos: link severed ({self._sever_left} left)")
            if n in self.drop_sends:
                self.dropped += 1
                return                      # the frame never arrives
            if n in self.delay_sends and self.delay > 0:
                self._sleep(self.delay)
            if n in self.corrupt_sends:
                self.corrupted += 1
                frame = self._flip_byte(frame)
            real_send(inner, frame)
            if n in self.duplicate_sends:
                self.duplicated += 1
                real_send(inner, frame)

        return _send

    def _recv_faulted(self, real_recv, inner):
        def _recv_bytes(timeout):
            data = real_recv(inner, timeout)
            self.recvs += 1
            if self.recvs in self.corrupt_recvs:
                self.corrupted += 1
                data = self._flip_byte(data)
            return data

        return _recv_bytes


class PartitionedLink:
    """Network-partition seam for one supervisor->host fleet link.

    Unlike :meth:`ChaosTransport.sever_for` (a count of failed send
    attempts), a partition is a STATE: while :meth:`sever` holds, every
    send raises `TransportSevered` immediately and every push frame the
    server emits is swallowed before the client sees it — nothing
    crosses in either direction until :meth:`heal`.  The supervisor's
    host-lease machinery (fleet.hosts) is proven against this seam: a
    severed host's replicas are fenced to a higher lease epoch and
    replayed elsewhere, and a healed host's survivors self-quarantine
    on first contact instead of double-serving.
    """

    def __init__(self, inner):
        self._inner = inner
        self.severed = False
        self.blocked_sends = 0
        self.blocked_push = 0
        # capture the BOUND send (which may already be chaos-spliced) so
        # partition composes with ChaosTransport fault schedules
        inner._send = self._send_gated(inner._send)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def epoch(self):
        """Lease fencing token — delegated so the supervisor's epoch
        stamp lands on the real transport, not the wrapper."""
        return self._inner.epoch

    @epoch.setter
    def epoch(self, value):
        self._inner.epoch = value

    def sever(self):
        self.severed = True

    def heal(self):
        self.severed = False

    def open_push(self, on_msg):
        """Push frames ride the same (conceptual) network: while the
        partition holds they are dropped client-side, exactly as a real
        severed connection would lose them — the pull path's event-log
        resync is what recovers the stream."""
        def gated(msg):
            if self.severed:
                self.blocked_push += 1
                return
            on_msg(msg)

        return self._inner.open_push(gated)

    def _send_gated(self, real_send):
        from paddle_tpu.inference.fleet.transport import TransportSevered

        def _send(frame):
            if self.severed:
                self.blocked_sends += 1
                raise TransportSevered("chaos: network partition")
            return real_send(frame)

        return _send
