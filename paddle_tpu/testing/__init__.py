"""paddle_tpu.testing — fault-injection and test harness utilities.

`chaos` is the fault-injection harness for the crash-safe checkpoint
stack (docs/CHECKPOINT.md): kill training subprocesses at chosen steps,
truncate/corrupt shard files, abort or delay checkpoint writes through
the writer's fault seam.
"""
from . import chaos  # noqa: F401
