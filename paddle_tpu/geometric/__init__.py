"""paddle.geometric — graph ops (parity: python/paddle/geometric):
message passing over segment ops (XLA scatter — the TPU-native form)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], reduce into dst (message_passing/send_recv parity)."""
    def _sur(x, src, dst):
        n = out_size or x.shape[0]
        msgs = x[src]
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dst, jnp.float32), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0)[:, None]
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, dst, num_segments=n)
        raise ValueError(reduce_op)

    return apply_op(_sur, x, src_index, dst_index, _op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    def _suer(x, y, src, dst):
        n = out_size or x.shape[0]
        msgs = x[src]
        if message_op == "add":
            msgs = msgs + y
        elif message_op == "mul":
            msgs = msgs * y
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dst, jnp.float32), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0)[:, None]
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n)
        raise ValueError(reduce_op)

    return apply_op(_suer, x, y, src_index, dst_index, _op_name="send_ue_recv")


def segment_sum(data, segment_ids, name=None):
    def _ss(d, ids):
        return jax.ops.segment_sum(d, ids, num_segments=int(ids.max()) + 1)

    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _ss2(d, ids):
        return jax.ops.segment_sum(d, ids, num_segments=n)

    return apply_op(_ss2, data, segment_ids, _op_name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sm(d, ids):
        s = jax.ops.segment_sum(d, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.float32), ids,
                                num_segments=n)
        return s / jnp.maximum(c, 1.0)[:, None]

    return apply_op(_sm, data, segment_ids, _op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sx(d, ids):
        return jax.ops.segment_max(d, ids, num_segments=n)

    return apply_op(_sx, data, segment_ids, _op_name="segment_max")


def segment_min(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sn(d, ids):
        return jax.ops.segment_min(d, ids, num_segments=n)

    return apply_op(_sn, data, segment_ids, _op_name="segment_min")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from both endpoints (message_passing send_uv)."""
    def _suv(xa, ya, src, dst):
        xs = xa[src]
        yd = ya[dst]
        if message_op == "add":
            return xs + yd
        if message_op == "sub":
            return xs - yd
        if message_op == "mul":
            return xs * yd
        if message_op == "div":
            return xs / yd
        raise ValueError(message_op)

    return apply_op(_suv, x, y, src_index, dst_index, _op_name="send_uv")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Host-side graph reindexing (sampling preprocessing)."""
    import numpy as np

    from ..core.tensor import Tensor

    xs = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    nb = np.asarray(neighbors.numpy() if hasattr(neighbors, "numpy")
                    else neighbors)
    nodes = np.concatenate([xs, nb])
    uniq, inverse = np.unique(nodes, return_inverse=True)
    # stable order: x first, then new neighbor nodes in appearance order
    order = {}
    out_nodes = []
    for n in nodes:
        if n not in order:
            order[n] = len(out_nodes)
            out_nodes.append(n)
    remap = np.asarray([order[n] for n in nb])
    return (Tensor(jnp.asarray(remap)),
            Tensor(jnp.asarray(np.asarray(out_nodes))),
            Tensor(jnp.asarray(np.arange(len(xs)))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    outs = [reindex_graph(x, nb, ct) for nb, ct in zip(neighbors, count)]
    return ([o[0] for o in outs], outs[0][1], outs[0][2])


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling on a CSC graph (host-side)."""
    import numpy as np

    from ..core.tensor import Tensor

    r = np.asarray(row.numpy() if hasattr(row, "numpy") else row)
    cp = np.asarray(colptr.numpy() if hasattr(colptr, "numpy") else colptr)
    nodes = np.asarray(input_nodes.numpy() if hasattr(input_nodes, "numpy")
                       else input_nodes)
    rng = np.random.RandomState(0)
    out_nb, out_cnt = [], []
    for n in nodes.reshape(-1):
        nbrs = r[cp[n]:cp[n + 1]]
        if sample_size > 0 and len(nbrs) > sample_size:
            nbrs = rng.choice(nbrs, sample_size, replace=False)
        out_nb.append(nbrs)
        out_cnt.append(len(nbrs))
    return (Tensor(jnp.asarray(np.concatenate(out_nb) if out_nb else
                               np.array([], r.dtype))),
            Tensor(jnp.asarray(np.asarray(out_cnt, np.int32))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    import numpy as np

    from ..core.tensor import Tensor

    r = np.asarray(row.numpy() if hasattr(row, "numpy") else row)
    cp = np.asarray(colptr.numpy() if hasattr(colptr, "numpy") else colptr)
    w = np.asarray(edge_weight.numpy() if hasattr(edge_weight, "numpy")
                   else edge_weight)
    nodes = np.asarray(input_nodes.numpy() if hasattr(input_nodes, "numpy")
                       else input_nodes)
    rng = np.random.RandomState(0)
    out_nb, out_cnt = [], []
    for n in nodes.reshape(-1):
        nbrs = r[cp[n]:cp[n + 1]]
        ws = w[cp[n]:cp[n + 1]]
        if sample_size > 0 and len(nbrs) > sample_size:
            p = ws / ws.sum()
            nbrs = rng.choice(nbrs, sample_size, replace=False, p=p)
        out_nb.append(nbrs)
        out_cnt.append(len(nbrs))
    return (Tensor(jnp.asarray(np.concatenate(out_nb) if out_nb else
                               np.array([], r.dtype))),
            Tensor(jnp.asarray(np.asarray(out_cnt, np.int32))))
