"""paddle.geometric — graph ops (parity: python/paddle/geometric):
message passing over segment ops (XLA scatter — the TPU-native form)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], reduce into dst (message_passing/send_recv parity)."""
    def _sur(x, src, dst):
        n = out_size or x.shape[0]
        msgs = x[src]
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dst, jnp.float32), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0)[:, None]
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, dst, num_segments=n)
        raise ValueError(reduce_op)

    return apply_op(_sur, x, src_index, dst_index, _op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    def _suer(x, y, src, dst):
        n = out_size or x.shape[0]
        msgs = x[src]
        if message_op == "add":
            msgs = msgs + y
        elif message_op == "mul":
            msgs = msgs * y
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones_like(dst, jnp.float32), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0)[:, None]
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, dst, num_segments=n)
        raise ValueError(reduce_op)

    return apply_op(_suer, x, y, src_index, dst_index, _op_name="send_ue_recv")


def segment_sum(data, segment_ids, name=None):
    def _ss(d, ids):
        return jax.ops.segment_sum(d, ids, num_segments=int(ids.max()) + 1)

    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _ss2(d, ids):
        return jax.ops.segment_sum(d, ids, num_segments=n)

    return apply_op(_ss2, data, segment_ids, _op_name="segment_sum")


def segment_mean(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sm(d, ids):
        s = jax.ops.segment_sum(d, ids, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.float32), ids,
                                num_segments=n)
        return s / jnp.maximum(c, 1.0)[:, None]

    return apply_op(_sm, data, segment_ids, _op_name="segment_mean")


def segment_max(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sx(d, ids):
        return jax.ops.segment_max(d, ids, num_segments=n)

    return apply_op(_sx, data, segment_ids, _op_name="segment_max")


def segment_min(data, segment_ids, name=None):
    import numpy as np

    ids = segment_ids.numpy() if hasattr(segment_ids, "numpy") else segment_ids
    n = int(np.asarray(ids).max()) + 1

    def _sn(d, ids):
        return jax.ops.segment_min(d, ids, num_segments=n)

    return apply_op(_sn, data, segment_ids, _op_name="segment_min")
