"""paddle.fft (parity: python/paddle/fft.py) — thin lowering onto jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply_op


def _mk1(name):
    jfn = getattr(jnp.fft, name)

    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=norm), x, _op_name=name)

    op.__name__ = name
    return op


def _mkn(name):
    jfn = getattr(jnp.fft, name)

    def op(x, s=None, axes=None, norm="backward", name_=None):
        kw = {}
        if axes is not None:
            kw["axes"] = tuple(axes)
        return apply_op(lambda a: jfn(a, s=s, norm=norm, **kw), x, _op_name=name)

    op.__name__ = name
    return op


fft = _mk1("fft")
ifft = _mk1("ifft")
rfft = _mk1("rfft")
irfft = _mk1("irfft")
hfft = _mk1("hfft")
ihfft = _mk1("ihfft")
fft2 = _mkn("fft2")
ifft2 = _mkn("ifft2")
rfft2 = _mkn("rfft2")
irfft2 = _mkn("irfft2")
fftn = _mkn("fftn")
ifftn = _mkn("ifftn")
rfftn = _mkn("rfftn")
irfftn = _mkn("irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x, _op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x, _op_name="ifftshift")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(
        lambda a: jnp.fft.irfft2(jnp.conj(a), s=s, axes=axes, norm=_inv(norm)),
        x, _op_name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(
        lambda a: jnp.conj(jnp.fft.rfft2(a, s=s, axes=axes, norm=_inv(norm))),
        x, _op_name="ihfft2")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(
        lambda a: jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes, norm=_inv(norm)),
        x, _op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op(
        lambda a: jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes, norm=_inv(norm))),
        x, _op_name="ihfftn")


def _inv(norm):
    return {"backward": "forward", "forward": "backward"}.get(norm, norm)
