"""Flagship decoder-only transformer family (GPT / LLaMA style).

Capability slot: the reference trains these through PaddleNLP on Fleet hybrid
parallel (BASELINE.md configs 4-5). Here the model is built from paddle_tpu
layers so the whole training step jit-compiles to one XLA program; parallel
training shards it over a Mesh via paddle_tpu.distributed.

Layout conventions are TPU-first: [batch, seq, heads, head_dim] attention
tensors feed the Pallas flash kernel; weights stay [in, out] so every matmul
is a single MXU dot_general.
"""
from __future__ import annotations

import math
import os

import jax

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.nn import functional as FF
from paddle_tpu.nn import functional as F


class GPTConfig:
    def __init__(
        self,
        vocab_size=50304,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        num_kv_heads=None,
        intermediate_size=None,
        max_seq_len=2048,
        norm_type="rmsnorm",
        act="swiglu",
        rope=True,
        dropout=0.0,
        tie_embeddings=True,
        dtype="float32",
        recompute=False,
        recompute_policy="full",
        pp_interleave=1,
        pp_schedule="1f1b",
        head_chunk=None,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.intermediate_size = intermediate_size or (
            int(8 * hidden_size / 3 / 128 + 1) * 128 if act == "swiglu" else 4 * hidden_size
        )
        self.max_seq_len = max_seq_len
        self.norm_type = norm_type
        self.act = act
        self.rope = rope
        self.dropout = dropout
        self.tie_embeddings = tie_embeddings
        self.dtype = dtype
        self.recompute = recompute
        # "full" = rerun the whole block in backward (lowest memory);
        # "dots" = save matmul/attention outputs, recompute elementwise only
        # (jax.checkpoint_policies selective remat — the standard single-chip
        # throughput/memory middle ground)
        self.recompute_policy = recompute_policy
        # virtual pipeline stages per device (VPP): bubble shrinks by 1/v
        self.pp_interleave = pp_interleave
        # "1f1b" (AD-reversed ring) or "zb" (zero-bubble: dgrad-only ring,
        # weight grads batched bubble-free after it — ZB-H1 analogue,
        # reference passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62)
        self.pp_schedule = pp_schedule
        # vocab-chunk size of the fused CE head (None = PTPU_CE_VCHUNK or
        # the module default; a memory-planner plan dimension alongside
        # batch x remat — docs/PERF.md)
        self.head_chunk = head_chunk


def llama_config(size="7b", **overrides):
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=4, vocab_size=1024, max_seq_len=512),
        "125m": dict(hidden_size=768, num_layers=12, num_heads=12, vocab_size=50304),
        "350m": dict(hidden_size=1024, num_layers=24, num_heads=16, vocab_size=50304),
        "1.3b": dict(hidden_size=2048, num_layers=24, num_heads=16, vocab_size=50304),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32, vocab_size=32000),
    }
    cfg = presets[size]
    cfg.update(overrides)
    return GPTConfig(**cfg)


def compute_loss(hidden, weight, labels, *, config=None, transpose_y=True,
                 ignore_index=-100):
    """LM-head matmul + CE dispatch — the ONE loss-head entry every GPT
    variant shares. Paths (telemetry gauge ``loss_head_mode``):

    - **chunked** (default): blockwise-LSE fused head
      (`nn.functional.fused_cross_entropy`) — neither the fp32 logits nor
      the grad-logits ``[tokens, vocab]`` tensor ever exists in HBM.
    - **sharded**: the vocab-sharded variant, selected when the head
      weight carries a ``_vocab_shard_axis`` marker
      (:meth:`GPTForCausalLMPipe.shard_lm_head`) over a live mesh axis —
      each tp shard reduces (max, lse, gold) scalars per token, never a
      logits all-gather.
    - **dense**: the reference path (full logits + ``F.cross_entropy``),
      kept for A/B and as the parity oracle.

    ``PTPU_LOSS_HEAD`` forces a path; the int8 head rides on the chunked/
    sharded kernels via the parity-gated default
    (``fused_cross_entropy.int8_head_enabled``). The chunk size comes
    from ``config.head_chunk`` (a planner dimension) or ``PTPU_CE_VCHUNK``.
    """
    from paddle_tpu.nn.functional import fused_cross_entropy as FCE

    mode = os.environ.get("PTPU_LOSS_HEAD", "").strip().lower()
    if mode not in ("", "dense", "chunked", "sharded"):
        raise ValueError(
            f"PTPU_LOSS_HEAD={mode!r}: expected dense|chunked|sharded")
    chunk = getattr(config, "head_chunk", None) if config is not None else None
    vocab = weight.shape[0] if transpose_y else weight.shape[-1]

    axis = getattr(weight, "_vocab_shard_axis", None)
    mesh = None
    if axis is not None and mode in ("", "sharded"):
        # the mesh the head was SHARDED over (shard_lm_head records it in
        # the weight's dist_attr) — not the ambient global mesh, which can
        # be absent or a different object under an explicit
        # ShardedTrainStep(mesh=...)
        da = getattr(weight, "_dist_attr", None)
        mesh = da.process_mesh if da is not None else None
        if mesh is None:
            from paddle_tpu.distributed.fleet import active_mesh

            mesh = active_mesh()
        if (mesh is None or axis not in mesh.dim_names
                or mesh.get_dim_size(axis) <= 1):
            axis, mesh = None, None
    if mode == "sharded" and axis is None:
        raise ValueError(
            "PTPU_LOSS_HEAD=sharded but the head weight carries no live "
            "_vocab_shard_axis marker — call shard_lm_head(mesh, axis) "
            "(or ShardedTrainStep(shard_vocab_head=...)) first")
    if mode == "chunked":
        axis, mesh = None, None

    if mode == "dense":
        n_tokens = 1
        for s in labels.shape:
            n_tokens *= int(s)
        FCE.record_head_mode("dense", False, n_tokens, vocab)
        logits = (paddle.matmul(hidden, weight, transpose_y=True)
                  if transpose_y else paddle.matmul(hidden, weight))
        return F.cross_entropy(
            logits.reshape([-1, vocab]), labels.reshape([-1]),
            ignore_index=ignore_index)

    return FCE.fused_chunked_cross_entropy(
        hidden, weight, labels, transpose_y=transpose_y, vocab_chunk=chunk,
        ignore_index=ignore_index, mesh=mesh, tp_axis=axis)


class Attention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_heads
        self.num_kv_heads = config.num_kv_heads
        self.head_dim = h // config.num_heads
        self.q_proj = nn.Linear(h, self.num_heads * self.head_dim, bias_attr=False)
        self.k_proj = nn.Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False)
        self.v_proj = nn.Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False)
        self.o_proj = nn.Linear(self.num_heads * self.head_dim, h, bias_attr=False)
        self.rope = config.rope
        self.dropout = config.dropout

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        if self.rope:
            q, k, _ = FF.fused_rotary_position_embedding(q, k, None)
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = k.repeat_interleave(rep, axis=2)
            v = v.repeat_interleave(rep, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            is_causal=True, training=self.training,
        )
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.o_proj(out)


class MLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        self.act = config.act
        if config.act == "swiglu":
            self.gate_proj = nn.Linear(h, m, bias_attr=False)
            self.up_proj = nn.Linear(h, m, bias_attr=False)
            self.down_proj = nn.Linear(m, h, bias_attr=False)
        else:
            self.fc1 = nn.Linear(h, m)
            self.fc2 = nn.Linear(m, h)

    def forward(self, x):
        if self.act == "swiglu":
            return self.down_proj(FF.swiglu(self.gate_proj(x), self.up_proj(x)))
        return self.fc2(F.gelu(self.fc1(x)))


class DecoderLayer(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        norm_cls = nn.RMSNorm if config.norm_type == "rmsnorm" else nn.LayerNorm
        self.input_norm = norm_cls(config.hidden_size)
        self.attn = Attention(config)
        self.post_attn_norm = norm_cls(config.hidden_size)
        self.mlp = MLP(config)
        self.dropout = config.dropout

    def forward(self, x, attn_mask=None):
        h = x + self.attn(self.input_norm(x), attn_mask)
        return h + self.mlp(self.post_attn_norm(h))


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig, layer_factory=None):
        super().__init__()
        self.config = config
        factory = layer_factory or (lambda: DecoderLayer(config))
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        if not config.rope:
            self.embed_pos = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.layers = nn.LayerList([factory() for _ in range(config.num_layers)])
        norm_cls = nn.RMSNorm if config.norm_type == "rmsnorm" else nn.LayerNorm
        self.final_norm = norm_cls(config.hidden_size)
        # quant-compute amax state (docs/QUANT.md) — only threaded on the
        # shared-scan path (_run_stacked); the per-layer module loop
        # never quantizes (its matmuls live inside nn.Linear)
        amax0 = _quant_buffer_state(config)
        if amax0 is not None:
            self.register_buffer("quant_amax", amax0)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        if not self.config.rope:
            pos = paddle.arange(input_ids.shape[1])
            x = x + self.embed_pos(pos)
        if self._shared_block_eligible(attn_mask):
            # scan-over-layers (docs/SCAN.md): the LayerList weights are
            # stacked [L, ...] at trace time and run through the SAME
            # _block_pure scan body as StackedDecoder — compile time and
            # program size flat in depth, remat anchors identical, and
            # float32-hex identical to the per-layer module loop below
            # (PTPU_SCAN_LAYERS=0 unrolls the shared body instead).
            x = self._run_stacked(x)
        elif self.config.recompute:
            from paddle_tpu.distributed.fleet.utils import recompute

            for layer in self.layers:
                x = recompute(layer, x, attn_mask)
        else:
            for layer in self.layers:
                x = layer(x, attn_mask)
        return self.final_norm(x)

    def _shared_block_eligible(self, attn_mask):
        """True when the stack can run through the shared _block_pure
        scan body: plain DecoderLayers of the rmsnorm+swiglu+rope family,
        no mask/dropout, no per-layer distributed placements (pp stage
        assignment and parallelize() marks operate on per-layer modules,
        which the stacked tree would silently drop)."""
        cfg = self.config
        if attn_mask is not None or cfg.dropout or not cfg.rope:
            return False
        if cfg.norm_type != "rmsnorm" or cfg.act != "swiglu":
            return False
        from paddle_tpu import amp as _amp

        if _amp.is_auto_cast_enabled():
            # the stack dispatches as ONE op here, which would bypass
            # amp's per-op white/black-list casting (the matmuls would
            # silently run fp32) — keep the module loop under autocast
            return False
        if any(type(l) is not DecoderLayer for l in self.layers):
            return False
        for l in self.layers:
            for _, p in l.named_parameters():
                if getattr(p, "_dist_attr", None) is not None:
                    return False
        from paddle_tpu.distributed.fleet import active_mesh

        mesh = active_mesh()
        if (mesh is not None and "pp" in mesh.dim_names
                and mesh.get_dim_size("pp") > 1):
            return False
        return True

    def _run_stacked(self, x):
        """Eligible LayerList stack through the shared scan body.

        Cost note (docs/SCAN.md): the per-layer weights are stacked
        INSIDE the program, so each step pays a decoder-weights
        concatenate the module loop never paid — the trade is steady-
        state copy bandwidth for depth-flat compile time, which is the
        right trade for the eager frontend's dev/CPU/small-model uses.
        Flagship-scale training stores weights stacked natively
        (StackedDecoder) and never restacks; if an eager model is
        compile-bound AND copy-sensitive, PTPU_SCAN_LAYERS=0 restores
        the copy-free unrolled program."""
        import jax.numpy as jnp
        from paddle_tpu.core.dispatch import apply_op

        cfg = self.config
        L = len(self.layers)
        flat = []
        for l in self.layers:
            obj = {"input_norm.weight": l.input_norm.weight,
                   "attn.q_proj.weight": l.attn.q_proj.weight,
                   "attn.k_proj.weight": l.attn.k_proj.weight,
                   "attn.v_proj.weight": l.attn.v_proj.weight,
                   "attn.o_proj.weight": l.attn.o_proj.weight,
                   "post_attn_norm.weight": l.post_attn_norm.weight,
                   "mlp.gate_proj.weight": l.mlp.gate_proj.weight,
                   "mlp.up_proj.weight": l.mlp.up_proj.weight,
                   "mlp.down_proj.weight": l.mlp.down_proj.weight}
            flat.extend(obj[suffix] for _, suffix in _BLOCK_PARAM_FIELDS)

        quant_buf = self._buffers.get("quant_amax")

        def _run(x, *params):
            amax = None
            if quant_buf is not None:
                amax = params[-1]
                params = params[:-1]
            tables = (_rope_tables(x.shape[1],
                                   cfg.hidden_size // cfg.num_heads)
                      if cfg.rope and os.environ.get("PTPU_ROPE_HOIST")
                      else None)
            policy, int8_names = (_resolve_remat(cfg) if cfg.recompute
                                  else (None, frozenset()))
            q_sites, q_dtype = _resolve_quant(cfg)
            if q_sites and amax is None:
                from paddle_tpu import quant as _quant

                amax = jnp.zeros((L, len(_quant.GEMM_SITES), 2,
                                  _quant.amax_hist_len()), jnp.float32)
            block = _make_block(cfg, tables=tables, int8_names=int8_names,
                                policy=policy, quant_sites=q_sites,
                                quant_dtype=q_dtype)
            n = len(_BLOCK_PARAM_FIELDS)
            per_layer = [params[i * n:(i + 1) * n] for i in range(L)]

            def _out(res, new_amax=None):
                if quant_buf is None:
                    return res
                return res, (amax if new_amax is None else new_amax)

            if scan_layers_enabled():
                stacked = tuple(jnp.stack([lp[k] for lp in per_layer])
                                for k in range(n))
                if q_sites:
                    out, new_amax = _scan_blocks(block, x, stacked,
                                                 amax=amax)
                    return _out(out, new_amax)
                return _out(_scan_blocks(block, x, stacked))
            if q_sites:
                out, new_amax = _unrolled_blocks(block, x, per_layer,
                                                 amax=amax)
                return _out(out, new_amax)
            return _out(_unrolled_blocks(block, x, per_layer))

        if quant_buf is not None:
            out = apply_op(_run, x, *flat, quant_buf,
                           _op_name="gpt_layer_stack")
            from paddle_tpu.core.tensor import Tensor

            out, new_amax = out
            quant_buf._data = (new_amax._data
                               if isinstance(new_amax, Tensor) else new_amax)
            return out
        return apply_op(_run, x, *flat, _op_name="gpt_layer_stack")


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig, layer_factory=None):
        super().__init__()
        self.config = config
        self.model = GPTModel(config, layer_factory)
        if config.tie_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, attn_mask=None):
        hidden = self.model(input_ids, attn_mask)
        if self.lm_head is None:
            return paddle.matmul(hidden, self.model.embed_tokens.weight, transpose_y=True)
        return self.lm_head(hidden)

    def loss(self, input_ids, labels):
        """Fused chunked-head LM loss: the [N, vocab] logits tensor never
        materializes (compute_loss dispatch; PTPU_LOSS_HEAD=dense restores
        the reference full-logits path)."""
        hidden = self.model(input_ids)
        if self.lm_head is None:
            return compute_loss(hidden, self.model.embed_tokens.weight,
                                labels, config=self.config, transpose_y=True)
        return compute_loss(hidden, self.lm_head.weight, labels,
                            config=self.config, transpose_y=False)


def causal_lm_loss(model, batch):
    input_ids, labels = batch
    return model.loss(input_ids, labels)


# ---------------------------------------------------------------------------
# Pipelined variant: stacked decoder parameters + compiled SPMD pipeline
# (parity: PaddleNLP GPTForCausalLMPipe over fleet PipelineLayer/1F1B;
#  reference runtime: fleet/meta_parallel/pipeline_parallel.py:242)
# ---------------------------------------------------------------------------
def _rope_at_positions(x, pos, base=10000.0):
    """Neox-style rope on [B, T, H, D] at absolute positions.

    ``pos``: [B] per-row start offsets (the kv-cache / paged-serving
    case) — every consumer (training forward, generate, the serving
    engine) shares THIS formula, so decode paths stay bit-identical to
    the training path."""
    import jax.numpy as jnp

    d = x.shape[-1]
    t = x.shape[1]
    p = (pos[:, None] + jnp.arange(t)[None, :]).astype(jnp.float32)
    inv = base ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    freqs = p[..., None] * inv                     # [B, T, d/2]
    sin = jnp.sin(freqs)[:, :, None, :]
    cos = jnp.cos(freqs)[:, :, None, :]
    return _rope_rotate(x, sin, cos)


def _rope_rotate(x, sin, cos):
    """Apply the half-split rotation given broadcast-ready sin/cos."""
    import jax.numpy as jnp

    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _rope_tables_at(p, d, base=10000.0):
    """sin/cos tables for an ARBITRARY position vector ``p`` [T],
    broadcast-ready for [B, T, H, D] activations: [1, T, 1, d/2] each.
    The ONE frequency formula every table consumer shares —
    :func:`_rope_tables` (positions 0..t-1) and the ring-attention
    region's zigzag-global-position tables
    (collectives/ring_attention.RingContext.rope_tables) both delegate
    here, so an engaged ring step can never rotate by different angles
    than the single-device program."""
    import jax.numpy as jnp

    inv = base ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    freqs = p.astype(jnp.float32)[:, None] * inv   # [T, d/2]
    return (jnp.sin(freqs)[None, :, None, :],
            jnp.cos(freqs)[None, :, None, :])


def _rope_tables(t, d, base=10000.0):
    """sin/cos tables for positions 0..t-1, broadcast-ready for
    [B, T, H, D] activations: shape [1, T, 1, d/2] each.

    Hoisting these out of the layer scan (computed ONCE per step instead
    of per layer per pass) removes 2 * L * (fwd + remat) transcendental
    sweeps from the train step — sin/cos of a [T, d/2] grid is ~1MB and
    becomes a saved checkpoint input, never recomputed in backward."""
    import jax.numpy as jnp

    return _rope_tables_at(jnp.arange(t, dtype=jnp.float32), d, base)


def _rope_pure(x, base=10000.0, tables=None):
    """Neox-style rope on [B, S, H, D] arrays (positions 0..S-1)."""
    if tables is not None:
        return _rope_rotate(x, *tables)
    import jax.numpy as jnp

    return _rope_at_positions(
        x, jnp.zeros((x.shape[0],), jnp.int32), base)


def _rms_pure(x, w, eps=1e-6):
    import os

    import jax
    import jax.numpy as jnp

    if os.environ.get("PTPU_PALLAS_RMS"):
        # A/B knob: the Pallas rms kernel saves its rstd residual (named
        # "rms_rstd") so selective-remat backward skips the variance
        # reduce instead of re-running it
        from ..ops.pallas import on_tpu_device

        rows = 1
        for s in x.shape[:-1]:
            rows *= s
        if on_tpu_device() and rows % 8 == 0:
            from ..ops.pallas.rms_norm import rms_norm

            return rms_norm(x, w, eps)
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return ((x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).astype(x.dtype)) * w


@jax.custom_vjp
def _ffn_i8(h2, wg, wu, wd):
    """Whole swiglu FFN (down(silu(h2@wg) * (h2@wu))) whose backward reads
    int8-saved gate/up instead of re-running the two big matmuls.

    Forward numerics are EXACT (the real bf16 gate/up feed silu/mul/down);
    the int8 round-trip only enters the BACKWARD — inside the silu'/mul
    factors and the wd weight-grad contraction — the same wide-backward
    discipline as the int8 LM head
    (incubate/nn/functional/__init__.py:_int8_head_core). Residuals are
    tagged (ffn_gate_q8 etc.) so a save_only_these_names remat policy
    keeps the int8 copies at HALF the HBM of bf16 saves (which OOM at
    1.3B/b4, docs/ROUND4_IDEAS.md:7-13). The down-proj lives INSIDE the
    vjp so its wgrad reconstructs silu(gate)*up from the saved int8 —
    nothing in this block's backward re-runs a forward matmul.

    Capability slot: the reference's recompute pass offers no middle
    ground between save-full and re-run
    (distributed/passes/auto_parallel_recompute.py); TPU-native extension."""
    return (jax.nn.silu(h2 @ wg) * (h2 @ wu)) @ wd


def _ffn_i8_fwd(h2, wg, wu, wd):
    from jax.ad_checkpoint import checkpoint_name

    from paddle_tpu.incubate.nn.functional import _quantize_rows_int8

    gate = h2 @ wg
    up = h2 @ wu
    qg, sg = _quantize_rows_int8(gate)
    qu, su = _quantize_rows_int8(up)
    qg = checkpoint_name(qg, "ffn_gate_q8")
    sg = checkpoint_name(sg, "ffn_gate_q8_s")
    qu = checkpoint_name(qu, "ffn_up_q8")
    su = checkpoint_name(su, "ffn_up_q8_s")
    return (jax.nn.silu(gate) * up) @ wd, (h2, wg, wu, wd, qg, sg, qu, su)


def _ffn_i8_bwd(res, g):
    import jax.numpy as jnp

    h2, wg, wu, wd, qg, sg, qu, su = res
    gate = (qg.astype(jnp.float32) * sg)
    up = (qu.astype(jnp.float32) * su)
    sig = jax.nn.sigmoid(gate)
    silu = gate * sig
    dsilu = sig * (1.0 + gate * (1.0 - sig))
    ffn = (silu * up).astype(h2.dtype)
    dffn = g @ wd.T
    dwd = jnp.einsum("bsm,bsh->mh", ffn, g).astype(wd.dtype)
    gf = dffn.astype(jnp.float32)
    dgate = (gf * up * dsilu).astype(h2.dtype)
    dup = (gf * silu).astype(h2.dtype)
    dh2 = dgate @ wg.T + dup @ wu.T
    dwg = jnp.einsum("bsh,bsm->hm", h2, dgate).astype(wg.dtype)
    dwu = jnp.einsum("bsh,bsm->hm", h2, dup).astype(wu.dtype)
    return dh2, dwg, dwu, dwd


_ffn_i8.defvjp(_ffn_i8_fwd, _ffn_i8_bwd)


def scan_layers_enabled():
    """``PTPU_SCAN_LAYERS`` master switch (docs/SCAN.md): the default
    (unset/1) runs the decoder stack as ONE ``lax.scan`` body over a
    leading-axis-stacked weight tree — trace time, XLA compile time, and
    serialized program size stay flat in depth. ``0``/``off`` keeps the
    python-unrolled per-layer loop: linear compile cost, but a bitwise
    escape hatch (float32-hex-proven parity with the scanned path and
    with the pre-scan per-layer module loop)."""
    return os.environ.get("PTPU_SCAN_LAYERS", "").strip().lower() not in (
        "0", "off", "false")


def _fused_ffn_active(tp_seams):
    """norm→ffn seam megakernel gate (``PTPU_FUSED_FFN``, or the
    umbrella ``PTPU_FUSED_SEAMS`` that also engages the addrms attn→norm
    seam). Precedence mirrors the PR 6 rules: engaged tp seams own the
    row/col matmul layouts (the megakernel's plain-matmul reads would
    force mid-block reshards against the seq-sharded residual), and
    ``PTPU_INT8_FFN`` keeps its own whole-FFN vjp."""
    if tp_seams is not None:
        return False
    if os.environ.get("PTPU_INT8_FFN"):
        return False
    env = (os.environ.get("PTPU_FUSED_FFN")
           or os.environ.get("PTPU_FUSED_SEAMS") or "")
    if env in ("", "0"):
        return False
    # device gate (mirrors _sdpa_pure/_addrms_active): off-TPU the
    # kernel would run in the Pallas INTERPRETER — orders of magnitude
    # slower than the unfused XLA seam. "interpret" opts in explicitly
    # (parity tests drive the real kernel code on the CPU mesh).
    from paddle_tpu.ops.pallas import on_tpu_device

    return on_tpu_device() or env == "interpret"


def _addrms_active(tp_seams, q_shape):
    """attn→norm seam: the fused residual-add+rms Pallas pass
    (``PTPU_FUSED_ADDRMS``, or the ``PTPU_FUSED_SEAMS`` umbrella)."""
    if tp_seams is not None:
        return False
    env = (os.environ.get("PTPU_FUSED_ADDRMS")
           or os.environ.get("PTPU_FUSED_SEAMS") or "")
    if env in ("", "0"):
        return False
    from paddle_tpu.nn.functional.flash_attention import _use_pallas

    return _use_pallas(q_shape)


def _sdpa_pure(q, k, v, causal=True):
    """Flagship attention dispatch. Calls the pallas kernel DIRECTLY when
    `_use_pallas` holds (no silent try/except fallback: a kernel failure
    here must be loud, because the selective-remat anchors in `_block_pure`
    are chosen from the same predicate and a silent fallback would leave
    attention with no saved residual at all).

    Inside an ENGAGED ring-attention region (docs/ATTENTION.md) the
    local tensors are one sep shard's zigzag token slice: attention
    routes through the kv ring over ``sep`` — per-hop flash compute
    overlapped with the ppermute rotation — instead of a local-only
    kernel call that would silently drop cross-shard attention."""
    from paddle_tpu.nn.functional.flash_attention import (
        _constrain_heads_over_mp,
        _use_pallas,
        sdpa_arrays,
    )

    from paddle_tpu.distributed.collectives import ring_attention as _ringmod

    ctx = _ringmod.active_ring_context()
    if ctx is not None:
        return _ringmod.ring_attention(q, k, v, ctx, causal=causal)
    if _use_pallas(q.shape):
        from paddle_tpu.ops.pallas import flash_attention as _flash_kernel

        q, k, v = _constrain_heads_over_mp(q, k, v)
        return _flash_kernel(q, k, v, causal=causal)
    return sdpa_arrays(q, k, v, causal=causal)


def _block_pure(p, x, num_heads, num_kv_heads, use_rope=True,
                rope_tables=None, int8_names=frozenset(), tp_seams=None,
                quant=None):
    """One decoder block on arrays. p = (ln1, wq, wk, wv, wo, ln2, wg, wu, wd).

    ``int8_names``: anchors whose save point is routed through
    ``memory.int8_checkpoint`` (blockwise-int8 + fp32 scales) instead of
    a bf16 ``checkpoint_name`` — what an ``int8:<anchor>`` entry in a
    ``names:`` recompute_policy requests. Each int8-saved tensor holds
    ~half the HBM of its bf16 save, buying batch or more saves.

    ``tp_seams``: a ``collectives.TPSeamPlan`` routing the row/col-
    parallel matmuls through the fused compute-collective kernels —
    ``o @ wo`` / ``ffn @ wd`` become matmul+reduce-scatter (the residual
    stream between seams stays SEQUENCE-SHARDED over the tp axis) and
    the q/k/v/gate/up projections become all-gather+matmul
    (docs/COMMS.md). None (the default, and always under pp or inside
    the quantized dp-grad region) keeps the GSPMD-emitted seams.

    ``quant``: a ``paddle_tpu.quant.GemmQuantCtx`` holding this layer's
    delayed-scaling amax state — engaged GEMM sites run the scaled
    fp8/int8 forward (backward stays wide/exact, docs/QUANT.md) and the
    caller collects the updated amax histories via ``quant.collect()``.
    Mutually exclusive with ``tp_seams`` (the seams own their matmul
    layouts — the engagement resolver declines quant first)."""
    import jax
    import jax.numpy as jnp

    from jax.ad_checkpoint import checkpoint_name

    def _save(t, name):
        if name in int8_names:
            from paddle_tpu.memory import int8_checkpoint

            return int8_checkpoint(t, name)
        return checkpoint_name(t, name)

    def _col(xx, w, site):  # column-parallel seam (x may be seq-sharded)
        if tp_seams is not None:
            return tp_seams.all_gather_matmul(xx, w)
        if quant is not None:
            return quant.gemm(xx, w, site)
        return xx @ w

    def _row(xx, w, site):  # row-parallel seam (output seq-sharded)
        if tp_seams is not None:
            return tp_seams.matmul_reduce_scatter(xx, w)
        if quant is not None:
            return quant.gemm(xx, w, site)
        return xx @ w

    ln1, wq, wk, wv, wo, ln2, wg, wu, wd = p
    b, s, hdim = x.shape
    hd = hdim // num_heads
    h = _rms_pure(x, ln1)
    # head counts and the attention seq length derive from the SEAM
    # output, not the config: inside a composed manual region
    # (collectives/compose) the block runs per shard — `_col` gathers
    # the seq-sharded stream (sq = s * tp) and its mp-sharded weight
    # yields the LOCAL head slice (num_heads/tp), while the plain and
    # island-seam paths see sq == s and the full head count. `-1` in the
    # reshape covers both without branching.
    q = _col(h, wq, "wq")
    sq = q.shape[1]
    q = q.reshape(b, sq, -1, hd)
    k = _col(h, wk, "wk").reshape(b, sq, -1, hd)
    v = _col(h, wv, "wv").reshape(b, sq, -1, hd)
    # engaged ring-attention region (docs/ATTENTION.md): this block sees
    # ONE sep shard's zigzag token slice, so rope must rotate by the
    # GLOBAL positions of those tokens (from the region's sep ordinal),
    # not 0..s — and hoisted local-position tables must not apply
    from paddle_tpu.distributed.collectives import ring_attention as _ringmod

    _ring_ctx = _ringmod.active_ring_context()
    if use_rope:
        if _ring_ctx is not None:
            rope_tables = _ring_ctx.rope_tables(s, hd)
        elif sq != s:
            # composed-seam path: the gathered attention stream covers
            # the FULL sequence; hoisted local-position tables (built
            # for the seq shard) must not apply
            rope_tables = None
        q = _rope_pure(q, tables=rope_tables)
        k = _rope_pure(k, tables=rope_tables)
    # remat anchors (inert under policies that don't name them): saving
    # post-rope q/k/v lets the flash backward skip re-running rms1 + the
    # three projections + rope
    q = _save(q, "attn_q")
    k = _save(k, "attn_k")
    v = _save(v, "attn_v")
    o = _sdpa_pure(q, k, v, causal=True).reshape(b, sq, -1)
    # selective-remat anchor for the XLA-fallback path: with
    # recompute_policy="attn" the backward reuses this tensor instead of
    # re-running attention (quadratic in seq). On the pallas path the
    # custom_vjp residuals carry their own "attn_res"/"attn_lse" names —
    # tagging here too would save the same activation twice, so skip.
    # The ring custom_vjp tags the same two names, so it skips too.
    from paddle_tpu.nn.functional.flash_attention import _use_pallas

    if _ring_ctx is None and not _use_pallas(q.shape):
        o = _save(o, "attn_out")
    if _addrms_active(tp_seams, q.shape):
        # fused residual-add + rms in one Pallas pass (named residuals
        # addrms_y/rms_rstd make the backward reuse, not re-run, it).
        # Engaged tp seams take precedence: mixing one plain-matmul
        # all-reduce seam into a seq-sharded block forces reshards
        # between the layouts and forfeits the seam win (docs/COMMS.md)
        from ..ops.pallas.add_rms_norm import add_rms_norm

        wo_out = (quant.gemm(o, wo, "wo") if quant is not None else o @ wo)
        x, h2 = add_rms_norm(wo_out, x, ln2)
    else:
        # anchors: resid_mid skips the o-proj re-run; ln2_out feeds the
        # gate/up recompute without re-running rms2. On the fused-seam
        # path _row returns the attn output SEQ-SHARDED, so the
        # residual add and rms below run on 1/tp of the rows
        x = _save(x + _row(o, wo, "wo"), "resid_mid")
        h2 = _save(_rms_pure(x, ln2), "ln2_out")
    if os.environ.get("PTPU_INT8_FFN") and tp_seams is None:
        # (seam precedence as above: _ffn_i8's plain matmuls would break
        # the seq-sharded layout mid-block)
        # int8-saved gate/up: exact forward, backward dequantises instead
        # of re-running the two matmuls (~9 TFLOP/step at 1.3B/b4).
        # MEASURED LOSING on v5e-16G (0.523-0.528 vs 0.547 baseline, r4:
        # quant bandwidth + fusion breakage > the FLOPs saved) and
        # SUPERSEDED in r5 by factored-AdamW freeing enough HBM to save
        # gate/up in bf16 outright (the ffn_gate/ffn_up names below).
        # Kept for memory-floor configs only.
        return x + _ffn_i8(h2, wg, wu, wd)
    # per-projection anchors: saving gate/up outputs individually lets a
    # policy trade ~67MB/layer (b4) for skipping that matmul's re-run
    gate = _save(_col(h2, wg, "wg"), "ffn_gate")
    up = _save(_col(h2, wu, "wu"), "ffn_up")
    if _fused_ffn_active(tp_seams):
        from ..ops.pallas.swiglu_down import swiglu_down, swiglu_down_supported

        if swiglu_down_supported(gate.shape, wd.shape):
            # norm→ffn seam megakernel: (silu(gate) * up) @ wd streamed
            # through VMEM — the [tokens, intermediate] swiglu product
            # never round-trips HBM. No "ffn_out" anchor on this path
            # (the custom_vjp backward rebuilds silu*up from the saved
            # gate/up, mirroring the pallas-attention anchor rule above,
            # so a policy naming ffn_out simply saves nothing for it —
            # the silu*mul replay is elementwise; docs/SCAN.md).
            return x + swiglu_down(gate, up, wd)
    ffn = _save(jax.nn.silu(gate) * up, "ffn_out")
    return x + _row(ffn, wd, "wd")


# ---------------------------------------------------------------------------
# Shared scan-over-layers machinery (docs/SCAN.md). The ONE block
# implementation is _block_pure; the helpers below turn it into a remat-
# wrapped scan body (or python-unrolled loop) shared by BOTH decoder
# frontends — StackedDecoder (weights stored [L, ...]) and the eager
# GPTModel LayerList (weights stacked at trace time) — so remat-anchor
# names cannot drift between them.
# ---------------------------------------------------------------------------
#: _block_pure's parameter order, as (StackedDecoder attr, per-layer
#: DecoderLayer state_dict suffix) pairs — also the stacked<->per-layer
#: checkpoint layout contract (convert_decoder_state_dict below)
_BLOCK_PARAM_FIELDS = (
    ("ln1", "input_norm.weight"),
    ("wq", "attn.q_proj.weight"),
    ("wk", "attn.k_proj.weight"),
    ("wv", "attn.v_proj.weight"),
    ("wo", "attn.o_proj.weight"),
    ("ln2", "post_attn_norm.weight"),
    ("wg", "mlp.gate_proj.weight"),
    ("wu", "mlp.up_proj.weight"),
    ("wd", "mlp.down_proj.weight"),
)


def _zero_jit_gather():
    """JIT slab-gather closure over _BLOCK_PARAM_FIELDS, or None when no
    dim-sharded slab is deferred (docs/ZERO.md stage-3) — shared by the
    pure-data zero path and the composed region."""
    from paddle_tpu.distributed.collectives import zero as _zero

    info = _zero.active_jit_gathers()
    if not info:
        return None
    ents = tuple(info.get(attr) for attr, _ in _BLOCK_PARAM_FIELDS)
    if not any(e is not None for e in ents):
        return None

    def gather(p, _ents=ents):
        # per-layer slice of a dim-d-sharded slab is sharded at d-1
        return tuple(
            w if e is None else _zero.gather_shard(
                w, e[0], e[1] - 1, degree=e[2], quantized=e[3])
            for w, e in zip(p, _ents))
    return gather


def _resolve_remat(cfg):
    """(checkpoint policy, int8 anchor names) for ``cfg.recompute_policy``
    — the single parser both decoder frontends share."""
    import jax

    int8_names = frozenset()
    pol = getattr(cfg, "recompute_policy", "full")
    policy = None
    if isinstance(pol, str) and pol.startswith("names:"):
        # free-form selective remat: comma-separated checkpoint_name tags
        # (the available anchors are tagged in _block_pure). An
        # int8:<anchor> entry saves that anchor as blockwise int8 + fp32
        # scales (memory.int8_checkpoint) at ~half the bf16 bytes.
        # quant:<site> entries belong to the quantized-compute resolver
        # (paddle_tpu.quant, docs/QUANT.md) — stripped before the save
        # names parse, they name GEMM sites rather than remat anchors.
        from paddle_tpu.memory import parse_save_names
        from paddle_tpu.quant import split_quant_entries

        spec, _ = split_quant_entries(pol[len("names:"):])
        save_names, int8_names = parse_save_names(spec)
        policy = jax.checkpoint_policies.save_only_these_names(*save_names)
    elif pol == "dots":
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    elif pol == "attn":
        policy = jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_res", "attn_lse")
    elif pol == "attn_ffn":
        policy = jax.checkpoint_policies.save_only_these_names(
            "attn_out", "attn_res", "attn_lse", "ffn_out")
    return policy, int8_names


def _resolve_quant(cfg, *, tp_seams=None, composed=False, pipelined=False,
                   path="train"):
    """Trace-time quantized-compute engagement for the shared scan body
    (docs/QUANT.md): ``(engaged sites, narrow dtype)``, with every
    resolution recorded as a structured ``quant_gemm`` plan verdict.

    Precedence mirrors the PR 6/7 rules: engaged tp seams own the
    row/col matmul layouts; the pipeline stage_fn and composed manual
    region don't thread amax state; a fused FFN kernel (``_ffn_i8`` /
    ``swiglu_down``) owns its GEMMs, dropping just those sites; and with
    ``PTPU_QUANT_COMPUTE`` unset the int8-head-style parity gate (CPU
    default-off) must pass."""
    from paddle_tpu import quant as _quant
    from paddle_tpu.distributed.collectives import compose as _compose

    sites = _quant.requested_quant_sites(cfg)
    if not sites:
        return frozenset(), None
    note = _compose.note_plan_engagement

    def _decline(reason):
        note("quant_gemm", reason)
        _quant.note_gemm_mode(path, frozenset(), None)
        return frozenset(), None

    if composed:
        return _decline(_compose.Reason.QUANT_COMPOSED)
    if pipelined:
        return _decline(_compose.Reason.QUANT_PIPELINE)
    if tp_seams is not None:
        return _decline(_compose.Reason.QUANT_SEAM)
    if not _quant.quant_compute_enabled(requested=True):
        return _decline(_compose.Reason.QUANT_GATE)
    if os.environ.get("PTPU_INT8_FFN"):
        owned = sites & {"wg", "wu", "wd"}
        if owned:
            note("quant_gemm", _compose.Reason.QUANT_FUSED_FFN)
            sites = sites - owned
    elif _fused_ffn_active(tp_seams) and "wd" in sites:
        # the swiglu_down megakernel consumes wd (and declines
        # pre-quantized operands — its VMEM stream is bf16-shaped);
        # gate/up stay quantizable, they feed the kernel post-GEMM
        note("quant_gemm", _compose.Reason.QUANT_FUSED_FFN)
        sites = sites - {"wd"}
    if not sites:
        return frozenset(), None
    dtype = _quant.quant_dtype()
    note("quant_gemm", _compose.Reason.ENGAGED)
    h = cfg.hidden_size
    kv = cfg.num_kv_heads * (h // cfg.num_heads)
    m = cfg.intermediate_size
    dims = {"wq": h * h, "wk": h * kv, "wv": h * kv, "wo": h * h,
            "wg": h * m, "wu": h * m, "wd": m * h}
    flops_per_token = 2 * sum(dims[s] for s in sites) * cfg.num_layers
    _quant.note_gemm_mode(path, sites, dtype, flops_per_token)
    return frozenset(sites), dtype


def _quant_buffer_state(config):
    """The fresh stacked delayed-scaling buffer for ``config``, or None
    when quant-compute is not requested (buffer presence tracks the
    REQUEST — policy ``quant:`` entries or the env force — not the
    parity gate, so a gate flake can't change checkpoint layout)."""
    from paddle_tpu import quant as _quant

    if not _quant.requested_quant_sites(config):
        return None
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    return Tensor(jnp.asarray(_quant.init_amax_state(config.num_layers)))


def _make_block(cfg, tables=None, int8_names=frozenset(), tp_seams=None,
                policy=None, gather=None, quant_sites=frozenset(),
                quant_dtype=None):
    """One remat-wrapped decoder block over arrays: the scan body. With
    ``cfg.recompute`` each body is a ``jax.checkpoint`` — the remat
    policy (including int8:<anchor> saves) applies PER LAYER whether the
    stack is scanned or unrolled.

    ``gather`` (ZeRO stage 3, docs/ZERO.md): a callable mapping the
    per-layer weight tuple of SHARDS to full weights (all-gather over
    the sharding axis). It runs INSIDE the ``jax.checkpoint`` wrapper,
    so the remat backward re-gathers each layer's weights instead of
    saving L full copies — the fsdp discipline that keeps resident
    decoder HBM at 1/degree.

    ``quant_sites`` (docs/QUANT.md): engaged scaled-GEMM sites. The body
    then takes ``p = (weights, amax_layer)`` and returns
    ``(x, new_amax_layer)`` — delayed-scaling state is an explicit
    input/output because ``jax.checkpoint`` demands a pure body (the
    scan threads it through the stacked ``[L, ...]`` amax buffer)."""
    import jax

    def block(x, p):
        qctx = None
        if quant_sites:
            from paddle_tpu.quant import GemmQuantCtx

            p, amax_l = p
            qctx = GemmQuantCtx(quant_sites, amax_l, quant_dtype)
        if gather is not None:
            p = gather(p)
        out = _block_pure(p, x, cfg.num_heads, cfg.num_kv_heads,
                          cfg.rope, rope_tables=tables,
                          int8_names=int8_names, tp_seams=tp_seams,
                          quant=qctx)
        if qctx is not None:
            return out, qctx.collect()
        return out

    if cfg.recompute:
        block = jax.checkpoint(block, policy=policy)
    return block


def _scan_blocks(block, x, stacked, min_unroll=1, amax=None):
    """Run ``block`` as a lax.scan over a [L, ...]-stacked weight tree —
    compile time and program size flat in depth.

    With ``amax`` (the stacked ``[L, sites, 2, H]`` delayed-scaling
    buffer, docs/QUANT.md) the scan carries it as a second xs leaf and
    collects each layer's updated histories as ys — returns
    ``(out, new_amax)``; the block must be quant-shaped
    (``_make_block(quant_sites=...)``)."""
    import jax

    # PTPU_UNROLL_LAYERS=N statically unrolls the scan N-wide: the
    # per-iteration dynamic-slice of every stacked weight (a real HBM
    # copy — profiled at >20% of device ops, r4) becomes a
    # constant-offset slice XLA can alias. Costs compile time linear
    # in N. ``min_unroll`` floors it: the ZeRO just-in-time gather path
    # asks for >= 2 so consecutive (gather_l, block_l) pairs share one
    # loop body and XLA's scheduler can issue layer l+1's slab gather
    # while layer l computes (the fsdp prefetch, docs/ZERO.md).
    unroll = max(int(os.environ.get("PTPU_UNROLL_LAYERS", "1")),
                 int(min_unroll))

    if amax is not None:
        def qstep(x, p):
            out, new_amax_l = block(x, p)
            return out, new_amax_l

        return jax.lax.scan(qstep, x, (tuple(stacked), amax),
                            unroll=max(1, unroll))

    def step(x, p):
        return block(x, p), None

    out, _ = jax.lax.scan(step, x, tuple(stacked), unroll=max(1, unroll))
    return out


def _unrolled_blocks(block, x, layer_params, amax=None):
    """The ``PTPU_SCAN_LAYERS=0`` escape hatch: a python loop over
    per-layer weight tuples — program size linear in depth, float32-hex
    identical to the scanned path (tests/test_scan_layers.py proves it).
    With ``amax`` it mirrors the quant-shaped scan: returns
    ``(out, new_amax)`` with the per-layer histories restacked."""
    if amax is not None:
        import jax.numpy as jnp

        new_rows = []
        for i, p in enumerate(layer_params):
            x, new_amax_l = block(x, (tuple(p), amax[i]))
            new_rows.append(new_amax_l)
        return x, jnp.stack(new_rows)
    for p in layer_params:
        x = block(x, tuple(p))
    return x


class StackedDecoder(nn.Layer):
    """All decoder blocks as leading-axis-stacked parameters [L, ...].

    TPU-first: a single lax.scan over layers (constant compile time at any
    depth) when pp is off; when the active mesh has a "pp" axis > 1, the
    leading axis is stage-sharded and the compiled SPMD pipeline schedule
    (distributed/pipeline.py) runs microbatches through ppermute rotation.
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        if config.norm_type != "rmsnorm" or config.act != "swiglu":
            raise ValueError("StackedDecoder supports the rmsnorm+swiglu family")
        if not config.rope:
            raise ValueError("StackedDecoder requires rope positions "
                             "(learned embed_pos is not supported)")
        if config.dropout:
            raise ValueError("StackedDecoder does not support dropout")
        from paddle_tpu.nn.initializer import Constant, Normal

        L, h = config.num_layers, config.hidden_size
        hd = h // config.num_heads
        kv = config.num_kv_heads * hd
        m = config.intermediate_size
        self.config = config
        w = lambda *shape: self.create_parameter(
            list(shape), default_initializer=Normal(std=0.02)
        )
        one = Constant(1.0)
        self.ln1 = self.create_parameter([L, h], default_initializer=one)
        self.wq = w(L, h, h)
        self.wk = w(L, h, kv)
        self.wv = w(L, h, kv)
        self.wo = w(L, h, h)
        self.ln2 = self.create_parameter([L, h], default_initializer=one)
        self.wg = w(L, h, m)
        self.wu = w(L, h, m)
        self.wd = w(L, m, h)
        # delayed-scaling amax state [L, sites, 2, H] (docs/QUANT.md):
        # registered only when quant-compute is REQUESTED, so unrequested
        # builds are structurally identical to pre-quant programs (the
        # PTPU_QUANT_COMPUTE=0 hex-identity contract). As a persistable
        # buffer it rides TrainStep/ShardedTrainStep threading, StepGuard
        # skip/rollback, and CheckpointManager like the RNG-key chain.
        amax0 = _quant_buffer_state(config)
        if amax0 is not None:
            self.register_buffer("quant_amax", amax0)

    def _mesh_pp(self):
        from paddle_tpu.distributed.fleet import active_mesh

        mesh = active_mesh()
        if mesh is None or "pp" not in mesh.dim_names:
            return None, 1
        return mesh, mesh.get_dim_size("pp")

    # Megatron TP dims of the stacked weights: column-parallel projections
    # shard their OUTPUT dim, row-parallel ones their INPUT dim (the mp
    # collectives are GSPMD-inserted: the pipeline shard_map keeps only
    # 'pp' manual, every other mesh axis stays auto)
    _TP_DIMS = {"wq": 2, "wk": 2, "wv": 2, "wg": 2, "wu": 2,
                "wo": 1, "wd": 1}

    def apply_tp_placements(self, mesh=None, tp_axis="mp"):
        """Megatron TP placements on a PIPELINE-FREE mesh: shard the
        projection weights' column/row dims (_TP_DIMS) over ``tp_axis``,
        leaving the stacked layer dim replicated. The pp x mp hybrid
        keeps using :meth:`apply_pipeline_placements`; this is the entry
        for pure-TP / dp x mp meshes where the fused compute-collective
        seams (distributed/collectives.fused, docs/COMMS.md) can own the
        row/col-parallel matmuls."""
        from paddle_tpu.distributed.auto_parallel import (
            Replicate, Shard, TensorDistAttr)

        if mesh is None:
            from paddle_tpu.distributed.fleet import active_mesh

            mesh = active_mesh()
        if (mesh is None or tp_axis not in mesh.dim_names
                or mesh.get_dim_size(tp_axis) <= 1):
            return self
        tp = mesh.get_dim_size(tp_axis)
        cfg = self.config
        for what, n in (("num_heads", cfg.num_heads),
                        ("num_kv_heads", cfg.num_kv_heads),
                        ("intermediate_size", cfg.intermediate_size)):
            if n % tp != 0:
                raise ValueError(f"tp_axis={tp_axis!r} (size {tp}) must "
                                 f"divide {what} ({n})")
        ax = mesh.dim_names.index(tp_axis)
        for name, p in self.named_parameters():
            leaf = name.rsplit(".", 1)[-1]
            if leaf not in self._TP_DIMS:
                continue
            placements = [Replicate() for _ in mesh.dim_names]
            placements[ax] = Shard(self._TP_DIMS[leaf])
            p._dist_attr = TensorDistAttr(mesh, placements)
        return self

    def apply_pipeline_placements(self, mesh=None, tp_axis=None):
        """Mark every stacked param Shard(0) over the 'pp' mesh axis.

        tp_axis="mp" additionally shards the projection weights over the
        tensor-parallel axis (column/row-parallel dims per _TP_DIMS), so
        one placement pass yields the full pp x mp hybrid — the
        fleet 3-axis composition (reference: pp->mp->dp group nesting,
        fleet/base/topology.py:298) expressed as GSPMD placements."""
        from paddle_tpu.distributed.auto_parallel import (
            Replicate, Shard, TensorDistAttr)

        if mesh is None:
            mesh, pp = self._mesh_pp()
            if mesh is None:
                return self
        ax = mesh.dim_names.index("pp")
        tp_ax = None
        if (tp_axis is not None and tp_axis in mesh.dim_names
                and mesh.get_dim_size(tp_axis) > 1):
            tp_ax = mesh.dim_names.index(tp_axis)
            cfg = self.config
            tp = mesh.get_dim_size(tp_axis)
            for what, n in (("num_heads", cfg.num_heads),
                            ("num_kv_heads", cfg.num_kv_heads),
                            ("intermediate_size", cfg.intermediate_size)):
                if n % tp != 0:
                    raise ValueError(
                        f"tp_axis={tp_axis!r} (size {tp}) must divide "
                        f"{what} ({n})")
        for name, p in self.named_parameters():
            placements = [Replicate() for _ in mesh.dim_names]
            placements[ax] = Shard(0)
            leaf = name.rsplit(".", 1)[-1]
            if tp_ax is not None and leaf in self._TP_DIMS:
                placements[tp_ax] = Shard(self._TP_DIMS[leaf])
            p._dist_attr = TensorDistAttr(mesh, placements)
        return self

    def _run_composed(self, ctx, x, params):
        """Composed-region decoder body (collectives/compose,
        docs/COMMS.md lattice): runs PER SHARD inside the step's ONE
        fully-manual region. The residual stream is sequence-sharded
        over mp between the in-region seams (seq_split/seq_unsplit are
        the hand-written transpose pair), ZeRO slab gathers defer into
        the scan body exactly as in the pure-data zero mode, and a live
        pipeline axis runs the explicit inline 1F1B/zero-bubble
        schedule (distributed/pipeline.py) over this shard's stage
        slab with the stage ordinal from the region's sharded iota."""
        cfg = self.config
        plan = ctx.plan
        policy, int8_names = (_resolve_remat(cfg) if cfg.recompute
                              else (None, frozenset()))
        gather = _zero_jit_gather()

        seams = ctx.seams
        # no hoisted rope tables here: the seq-sharded stream's local
        # positions are not the attention stream's (the seam gather
        # restores the full sequence; _block_pure rotates inline)
        block = _make_block(cfg, tables=None, int8_names=int8_names,
                            tp_seams=seams, policy=policy, gather=gather)
        ctx.decoder_calls += 1
        if seams is not None:
            x = seams.seq_split(x)
        if plan.pp_axis:
            x = ctx.pipeline_apply(block, x, params,
                                   gather=gather is not None)
        elif scan_layers_enabled():
            x = _scan_blocks(block, x, params,
                             min_unroll=2 if gather else 1)
        else:
            L = int(params[0].shape[0])
            x = _unrolled_blocks(
                block, x,
                (tuple(w[i] for w in params) for i in range(L)))
        if seams is not None:
            x = seams.seq_unsplit(x)
        return x

    def forward(self, x):
        import jax
        from paddle_tpu.core.dispatch import apply_op

        cfg = self.config
        mesh, pp = self._mesh_pp()
        quant_buf = self._buffers.get("quant_amax")

        def _run(x, *params):
            import os

            from paddle_tpu.distributed.collectives import (
                compose as _compose)

            # quant-compute amax state rides as the last operand when the
            # buffer exists; declined paths pass it through unchanged so
            # the output structure stays (x, amax) either way
            amax = None
            if quant_buf is not None:
                amax = params[-1]
                params = params[:-1]

            def _out(res, new_amax=None):
                if quant_buf is None:
                    return res
                return res, (amax if new_amax is None else new_amax)

            _ctx = _compose.active_composed_context()
            if _ctx is not None:
                _resolve_quant(cfg, composed=True)
                return _out(self._run_composed(_ctx, x, params))

            # PTPU_ROPE_HOIST=1 precomputes sin/cos tables once per step
            # outside the scan. Measured SLOWER on v5e (0.5007 vs 0.5072 MFU
            # A/B, r3): XLA fuses the inline sin/cos into the rotation's
            # elementwise kernel for free, while hoisted tables add per-layer
            # HBM reads. Kept as a knob — the tradeoff may flip at longer
            # sequences where the table amortises more transcendentals.
            tables = (_rope_tables(x.shape[1], cfg.hidden_size // cfg.num_heads)
                      if cfg.rope and os.environ.get("PTPU_ROPE_HOIST")
                      else None)

            policy, int8_names = (_resolve_remat(cfg) if cfg.recompute
                                  else (None, frozenset()))

            # fused tp seams (docs/COMMS.md): owned matmul+reduce-scatter /
            # all-gather+matmul kernels replace the GSPMD-emitted mp
            # collectives at the row/col-parallel seams. Resolved per
            # trace — plan_tp_seams returns None under pp, inside the
            # quantized dp-grad manual region, with PTPU_TP_SEAM=0, or
            # when no tp placement is live on the stacked weights.
            tp_seams = None
            if pp <= 1:
                da = getattr(self.wq, "_dist_attr", None)
                if da is not None:
                    from paddle_tpu.distributed.auto_parallel import Shard
                    from paddle_tpu.distributed import collectives

                    # DATA axes are never tp axes: ZeRO stage-3 marks
                    # (shard_model_parameters) also land Shard(dim>0)
                    # placements over "sharding" — treating one as a
                    # Megatron tp placement built seam specs naming the
                    # same mesh axis twice (duplicate-axis ValueError)
                    tp_axes = [
                        a for a, pl in zip(da.process_mesh.dim_names,
                                           da.placements)
                        if isinstance(pl, Shard) and pl.dim > 0
                        and a not in ("dp", "sharding")]
                    if len(tp_axes) == 1:
                        tp_seams = collectives.plan_tp_seams(
                            da.process_mesh, tp_axis=tp_axes[0])

            # ZeRO stage-3 just-in-time slab gathers (docs/ZERO.md): the
            # ShardedTrainStep's manual region passes the stacked
            # weights in as their 1/degree dim shards and opens this
            # scope; each sharded slab gathers per layer INSIDE the
            # remat-wrapped scan body (backward re-gathers), and AD of
            # the gather reduce-scatters the slab grads.
            gather = (_zero_jit_gather()
                      if pp <= 1 and tp_seams is None else None)

            # quantized-compute engagement (docs/QUANT.md): resolved per
            # trace against the live path — engaged tp seams and the
            # pipeline stage_fn decline with a structured reason
            q_sites, q_dtype = _resolve_quant(cfg, tp_seams=tp_seams,
                                              pipelined=pp > 1)
            if q_sites and amax is None:
                # env-forced quant on a model built without the buffer:
                # run stateless (all-zero histories bootstrap from the
                # current step's amax — the inline-scaling recipe)
                import jax.numpy as jnp

                from paddle_tpu import quant as _quant

                amax = jnp.zeros(
                    (cfg.num_layers, len(_quant.GEMM_SITES), 2,
                     _quant.amax_hist_len()), jnp.float32)

            block = _make_block(cfg, tables=tables, int8_names=int8_names,
                                tp_seams=tp_seams, policy=policy,
                                gather=gather, quant_sites=q_sites,
                                quant_dtype=q_dtype)

            if pp <= 1:
                if scan_layers_enabled():
                    if q_sites:
                        out, new_amax = _scan_blocks(
                            block, x, params,
                            min_unroll=2 if gather else 1, amax=amax)
                        return _out(out, new_amax)
                    return _out(_scan_blocks(
                        block, x, params, min_unroll=2 if gather else 1))
                # PTPU_SCAN_LAYERS=0 escape hatch: python-unrolled loop
                # over constant-offset slices of the stacked weights —
                # program size linear in depth, numerics bitwise equal
                L = int(params[0].shape[0])
                per_layer = (tuple(w[i] for w in params) for i in range(L))
                if q_sites:
                    out, new_amax = _unrolled_blocks(block, x, per_layer,
                                                     amax=amax)
                    return _out(out, new_amax)
                return _out(_unrolled_blocks(block, x, per_layer))

            def step(x, p):
                return block(x, p), None

            # a hybrid pipeline mesh outside the composed path would
            # open a PARTIAL-manual shard_map over 'pp' — this
            # container's XLA hard-ABORTS the partitioner on
            # CollectivePermute with manual subgroups (docs/COMMS.md
            # runtime limits), killing the whole process instead of
            # raising. Refuse loudly first; the composed hybrid step
            # (collectives/compose) is the supported lowering here.
            live_others = [a for a in mesh.dim_names
                           if a != "pp" and mesh.get_dim_size(a) > 1]
            if live_others and jax.default_backend() == "cpu":
                raise RuntimeError(
                    "pipeline parallelism with other live mesh axes "
                    f"({'/'.join(live_others)}) cannot lower as a "
                    "partial-manual shard_map on this XLA build — use "
                    "the composed hybrid step (ShardedTrainStep over "
                    "the full mesh, docs/COMMS.md lattice) or a "
                    "pp-only mesh. If composition was declined, the "
                    "plan_engagement telemetry names the reason "
                    "(tools/telemetry_report.py -- plans --).")

            from paddle_tpu.distributed.pipeline import (
                microbatch, spmd_pipeline, spmd_pipeline_interleaved,
                spmd_pipeline_zero_bubble,
                spmd_pipeline_zero_bubble_interleaved, unmicrobatch)

            def stage_fn(stage_params, x):
                out, _ = jax.lax.scan(step, x, stage_params)
                return out

            from jax.sharding import PartitionSpec as P

            v = getattr(cfg, "pp_interleave", 1) or 1
            n_micro = getattr(cfg, "pp_microbatches", None) or pp
            zb = getattr(cfg, "pp_schedule", "1f1b") == "zb"
            if v > 1:
                if cfg.num_layers % (pp * v) != 0:
                    raise ValueError(
                        f"pp_interleave={v} needs num_layers "
                        f"({cfg.num_layers}) divisible by pp*v ({pp * v})")
                mk = (spmd_pipeline_zero_bubble_interleaved if zb
                      else spmd_pipeline_interleaved)
                pipe = mk(stage_fn, mesh.jax_mesh, pp, v,
                          remat=cfg.recompute)
            elif zb:
                pipe = spmd_pipeline_zero_bubble(
                    stage_fn, mesh.jax_mesh, pp,
                    params_spec=P("pp"), remat=cfg.recompute)
            else:
                pipe = spmd_pipeline(
                    stage_fn, mesh.jax_mesh, pp,
                    params_spec=P("pp"), remat=cfg.recompute,
                )
            return _out(unmicrobatch(pipe(tuple(params),
                                          microbatch(x, n_micro))))

        operands = [x, self.ln1, self.wq, self.wk, self.wv, self.wo,
                    self.ln2, self.wg, self.wu, self.wd]
        if quant_buf is not None:
            operands.append(quant_buf)
        out = apply_op(_run, *operands, _op_name="stacked_decoder")
        if quant_buf is not None:
            from paddle_tpu.core.tensor import Tensor

            out, new_amax = out
            quant_buf._data = (new_amax._data
                               if isinstance(new_amax, Tensor) else new_amax)
        return out


class GPTForCausalLMPipe(nn.Layer):
    """Decoder-only LM with the stacked/pipelined decoder core."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        if not config.tie_embeddings:
            raise ValueError("GPTForCausalLMPipe ties the lm head to the "
                             "token embedding (tie_embeddings=False is not "
                             "supported)")
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.decoder = StackedDecoder(config)
        self.final_norm = nn.RMSNorm(config.hidden_size)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        x = self.decoder(x)
        x = self.final_norm(x)
        return paddle.matmul(x, self.embed_tokens.weight, transpose_y=True)

    def loss(self, input_ids, labels):
        """Fused tied-head LM loss: hidden @ embed^T + CE computed
        blockwise over VOCAB chunks (custom_vjp recomputes per-chunk
        logits in backward), so neither the fp32 logits nor the
        grad-logits [N, vocab] tensor ever hits HBM — ~1GB+1GB per
        microbatch at 1.3B/seq2048/batch4. With a vocab-sharded head
        (shard_lm_head) each tp shard reduces scalars per token instead
        of all-gathering logits."""
        x = self.embed_tokens(input_ids)
        x = self.decoder(x)
        x = self.final_norm(x)
        return compute_loss(x, self.embed_tokens.weight, labels,
                            config=self.config, transpose_y=True)

    def shard_lm_head(self, mesh, axis="mp"):
        """Last-stage-sharded pipeline output: place the tied
        head/embedding's VOCAB dim over the tensor-parallel axis instead
        of replicating it. The loss path (compute_loss) sees the marker
        and switches to the vocab-sharded CE — partial per-shard
        (max, lse, gold) combined with psum of scalars per token; on a
        pp mesh the last stage then holds 1/tp of the head instead of a
        full replica. Embedding lookups against the sharded table lower
        to GSPMD's gather+collective (the Megatron parallel-vocab
        recipe)."""
        from paddle_tpu.distributed.auto_parallel import (
            Replicate, Shard, TensorDistAttr)

        if axis not in mesh.dim_names or mesh.get_dim_size(axis) <= 1:
            return self
        if self.config.vocab_size % mesh.get_dim_size(axis) != 0:
            raise ValueError(
                f"the {axis!r} mesh axis (size {mesh.get_dim_size(axis)}) "
                f"must divide vocab_size ({self.config.vocab_size})")
        w = self.embed_tokens.weight
        placements = [Replicate() for _ in mesh.dim_names]
        placements[mesh.dim_names.index(axis)] = Shard(0)
        w._dist_attr = TensorDistAttr(mesh, placements)
        w._vocab_shard_axis = axis
        return self

    def _decode_params(self):
        """Per-layer slices of the stacked decoder weights — the serving/
        decode contract shared with LlamaForCausalLM (llama.py:66), so
        the flagship pipelined model serves through
        inference.ContinuousBatchingEngine unchanged.

        jnp indexing COPIES, so materializing every layer up front held a
        second full copy of the decoder for as long as the returned list
        lived — and a reload_weights() on a live engine transiently held
        THREE (stacked + old slices + new slices, ADVICE r5). Returns a
        lazy sequence instead: each layer is sliced on access and nothing
        is retained here, so consumers that process layers one at a time
        (the engine's _pack_weights) peak at stacked + one layer + their
        own copy. The engine's packed copy itself is inherent while the
        training model stays alive; for serving at flagship sizes, drop
        the training model after engine construction."""
        names = ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu", "wd")
        return _LazyLayerSlices(self.decoder, names, self.config.num_layers)


class _LazyLayerSlices:
    """Sequence of per-layer weight dicts over a StackedDecoder, sliced on
    access (each ``__getitem__`` copies ONE layer's weights; nothing is
    cached). Satisfies the ``_decode_params`` contract: len(), indexing,
    and iteration yield ``{name: obj-with-._data}`` per layer."""

    def __init__(self, decoder, names, num_layers):
        self._decoder = decoder
        self._names = names
        self._num_layers = num_layers

    def __len__(self):
        return self._num_layers

    def __getitem__(self, i):
        from types import SimpleNamespace

        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._num_layers))]
        if i < 0:
            i += self._num_layers
        if not 0 <= i < self._num_layers:
            raise IndexError(i)
        d = self._decoder
        return {n: SimpleNamespace(_data=getattr(d, n)._data[i])
                for n in self._names}

    def __iter__(self):
        for i in range(self._num_layers):
            yield self[i]


# ---------------------------------------------------------------------------
# Stacked <-> per-layer checkpoint layout conversion (docs/SCAN.md).
# The scanned flagship stores decoder weights [L, ...]-stacked
# (GPTForCausalLMPipe: "decoder.wq"), the eager LayerList family stores
# them per layer ("model.layers.{i}.attn.q_proj.weight"). A checkpoint
# written under either layout restores into the other BIT-FOR-BIT through
# these converters — old per-layer checkpoints keep working after a model
# is promoted to the stacked layout, and vice versa.
# ---------------------------------------------------------------------------
_SUFFIX_TO_ATTR = {suffix: attr for attr, suffix in _BLOCK_PARAM_FIELDS}
#: top-level (non-decoder) key mapping: stacked-side name -> per-layer name
_TOP_KEY_MAP = {"embed_tokens.weight": "model.embed_tokens.weight",
                "final_norm.weight": "model.final_norm.weight"}


def _raw_array(v):
    return v._data if hasattr(v, "_data") else v


def _split_opt_key(key):
    """("opt." or "", param-ish remainder). Optimizer entries are saved
    as "opt.<param_name>.<slot>" (distributed.checkpoint)."""
    return ("opt.", key[4:]) if key.startswith("opt.") else ("", key)


def _match_layer_key(rest):
    """per-layer decoder key -> (layer_index, attr, slot_suffix) or None.
    rest: "model.layers.3.attn.q_proj.weight[.slot]"."""
    prefix = "model.layers."
    if not rest.startswith(prefix):
        return None
    tail = rest[len(prefix):]
    idx, _, tail = tail.partition(".")
    if not idx.isdigit():
        return None
    for suffix, attr in _SUFFIX_TO_ATTR.items():
        if tail == suffix:
            return int(idx), attr, ""
        if tail.startswith(suffix + "."):
            return int(idx), attr, tail[len(suffix):]
    return None


def _match_stacked_key(rest):
    """stacked decoder key -> (attr, slot_suffix) or None.
    rest: "decoder.wq[.slot]"."""
    if not rest.startswith("decoder."):
        return None
    tail = rest[len("decoder."):]
    attr, _, slot = tail.partition(".")
    if attr not in _SUFFIX_TO_ATTR.values():
        return None
    return attr, ("." + slot if slot else "")


def decoder_state_layout(state):
    """"stacked" | "per_layer" | None for a LM state_dict's key set."""
    for key in state:
        _, rest = _split_opt_key(key)
        if _match_stacked_key(rest) is not None:
            return "stacked"
        if _match_layer_key(rest) is not None:
            return "per_layer"
    return None


def convert_decoder_state_dict(state, target):
    """Convert a GPT/LLaMA LM state_dict (params + optional
    "opt.<param>.<slot>" optimizer entries) to ``target`` ("stacked" |
    "per_layer"). Decoder weights are stacked/sliced along the leading
    layer axis bit-for-bit; param-shaped and factored slot entries follow
    their parameter, scalar slots (beta power accumulators) replicate on
    unstacking and must agree bitwise on stacking. Already-converted and
    unknown keys pass through unchanged (a strict restore then reports
    them). Blockwise-int8 moment slots do NOT convert exactly (their
    quant-block grid spans the stacked axis) — restore those under the
    layout that wrote them."""
    import numpy as np
    import jax.numpy as jnp

    if target not in ("stacked", "per_layer"):
        raise ValueError(f"target={target!r}: expected stacked|per_layer")
    out = {}
    if target == "stacked":
        pending = {}  # (pre, attr, slot) -> {layer_index: array}
        for key, v in state.items():
            pre, rest = _split_opt_key(key)
            m = _match_layer_key(rest)
            if m is None:
                new = rest
                for stacked_k, layer_k in _TOP_KEY_MAP.items():
                    if rest == layer_k:
                        new = stacked_k
                    elif rest.startswith(layer_k + "."):
                        new = stacked_k + rest[len(layer_k):]
                out[pre + new] = _raw_array(v)
                continue
            i, attr, slot = m
            pending.setdefault((pre, attr, slot), {})[i] = _raw_array(v)
        for (pre, attr, slot), by_layer in pending.items():
            L = max(by_layer) + 1
            missing = [i for i in range(L) if i not in by_layer]
            if missing:
                raise ValueError(
                    f"per-layer state is missing layers {missing} of "
                    f"{attr}{slot} (found {sorted(by_layer)})")
            arrs = [by_layer[i] for i in range(L)]
            if getattr(arrs[0], "ndim", 0) == 0:
                ref = np.asarray(arrs[0])
                for i, a in enumerate(arrs[1:], 1):
                    if np.asarray(a).tobytes() != ref.tobytes():
                        raise ValueError(
                            f"scalar slot {attr}{slot} differs between "
                            f"layers 0 and {i} — cannot collapse into one "
                            "stacked entry")
                out[pre + "decoder." + attr + slot] = arrs[0]
            else:
                out[pre + "decoder." + attr + slot] = jnp.stack(
                    [jnp.asarray(a) for a in arrs])
        return out

    # target == "per_layer"
    num_layers = None
    for key, v in state.items():
        _, rest = _split_opt_key(key)
        m = _match_stacked_key(rest)
        if m is not None and m[1] == "":
            num_layers = int(_raw_array(v).shape[0])
            break
    for key, v in state.items():
        pre, rest = _split_opt_key(key)
        m = _match_stacked_key(rest)
        if m is None:
            new = rest
            for stacked_k, layer_k in _TOP_KEY_MAP.items():
                if rest == stacked_k:
                    new = layer_k
                elif rest.startswith(stacked_k + "."):
                    new = layer_k + rest[len(stacked_k):]
            out[pre + new] = _raw_array(v)
            continue
        attr, slot = m
        suffix = dict(_BLOCK_PARAM_FIELDS)[attr]
        arr = _raw_array(v)
        if num_layers is None:
            raise ValueError("cannot infer num_layers: no stacked decoder "
                             "parameter entry in the state dict")
        for i in range(num_layers):
            per = (arr[i] if getattr(arr, "ndim", 0) >= 1
                   and arr.shape[0] == num_layers else arr)
            out[f"{pre}model.layers.{i}.{suffix}{slot}"] = per
    return out


def restore_decoder_any_layout(manager, model, optimizer=None, step=None,
                               strict=True):
    """``CheckpointManager.restore_training_state`` that also accepts a
    checkpoint written under the OTHER decoder layout: a per-layer
    (eager GPTForCausalLM / LLaMA) checkpoint restores into a stacked
    GPTForCausalLMPipe model bit-for-bit, and vice versa. A metadata-only
    layout peek routes same-layout checkpoints through the exact
    pre-existing native restore (reshard-on-load, the caller's
    ``strict``); other-layout checkpoints go through
    ``manager.read_state`` + :func:`convert_decoder_state_dict`.
    Returns the step restored."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.checkpoint import (
        MissingKeysError, _training_state_target)

    # Metadata-only layout peek decides the path BEFORE loading
    # anything: a same-layout checkpoint (including a lenient
    # strict=False partial restore) keeps the exact native
    # reshard-on-load path; only a genuinely other-layout checkpoint
    # pays the convert. (Deciding by probing the native restore instead
    # would either let a non-strict cross-layout restore "succeed"
    # loading nothing, or reroute lenient same-layout restores through
    # the converter and lose their resharding.)
    want = decoder_state_layout(model.state_dict())
    have = decoder_state_layout(manager.saved_keys(step=step))
    if want is None or have is None or have == want:
        try:
            return manager.restore_training_state(model, optimizer,
                                                  step=step, strict=strict)
        except MissingKeysError:
            if want is None:
                raise
            # mixed-layout root: the newest good step (whose layout the
            # peek saw) failed payload validation and the native walk
            # fell back onto an OTHER-layout older step — convert that
            # one below. (Residual corner: under strict=False such a
            # walk cannot raise and loads nothing from the other-layout
            # step; mixed-layout roots should restore with strict=True.)
    state, s = manager.read_state(step=step)
    target, finalize = _training_state_target(model, optimizer)
    want = decoder_state_layout(target) or "per_layer"
    conv = convert_decoder_state_dict(state, want)
    missing = [k for k in target if k not in conv]
    if missing and strict:
        raise MissingKeysError(
            f"checkpoint step {s} (converted to {want} layout) holds no "
            f"payload for: {sorted(missing)[:8]}"
            + ("..." if len(missing) > 8 else ""))
    import jax

    for k, t in target.items():
        if k not in conv:
            continue
        arr = jnp.asarray(conv[k])
        if tuple(arr.shape) != tuple(t.shape):
            raise ValueError(
                f"{k}: converted shape {tuple(arr.shape)} != model shape "
                f"{tuple(t.shape)}")
        # keep the target's placement: a parameter already device_put on
        # a mesh must not silently degrade to a replicated host array
        t._data = jax.device_put(arr.astype(t._data.dtype),
                                 t._data.sharding)
    finalize()
    return s


# ---------------------------------------------------------------------------
# MoE variant (parity slot: PaddleNLP MoE GPT over incubate MoELayer)
# ---------------------------------------------------------------------------
class MoEDecoderLayer(nn.Layer):
    """Decoder block whose MLP is a mixture of experts."""

    def __init__(self, config: GPTConfig, num_experts=8, top_k=2,
                 gate="gshard", capacity_factor=2.0):
        super().__init__()
        from paddle_tpu.incubate.distributed.models.moe import (
            MoELayer, StackedExperts)

        norm_cls = nn.RMSNorm if config.norm_type == "rmsnorm" else nn.LayerNorm
        self.input_norm = norm_cls(config.hidden_size)
        self.attn = Attention(config)
        self.post_attn_norm = norm_cls(config.hidden_size)
        self.moe = MoELayer(
            config.hidden_size,
            StackedExperts(num_experts, config.hidden_size,
                           config.intermediate_size),
            gate={"type": gate, "top_k": top_k},
            capacity_factor=capacity_factor,
        )

    def forward(self, x, attn_mask=None):
        h = x + self.attn(self.input_norm(x), attn_mask)
        return h + self.moe(self.post_attn_norm(h))


class GPTForCausalLMMoE(GPTForCausalLM):
    """Decoder LM with MoE FFNs; aux losses summed into .loss().

    Reuses the GPTModel scaffolding (embed/pos/recompute/final-norm/tied
    head) via the layer factory — only the block type differs."""

    def __init__(self, config: GPTConfig, num_experts=8, top_k=2,
                 gate="gshard", aux_loss_weight=0.01, capacity_factor=2.0):
        if not config.tie_embeddings:
            raise ValueError("GPTForCausalLMMoE ties the lm head to the "
                             "token embedding")
        if gate == "switch" and top_k != 1:
            raise ValueError("switch gate is top-1: pass top_k=1")
        super().__init__(config, layer_factory=lambda: MoEDecoderLayer(
            config, num_experts, top_k, gate, capacity_factor))
        self.aux_loss_weight = aux_loss_weight

    @property
    def layers(self):
        return self.model.layers

    def aux_loss(self):
        total = None
        for layer in self.model.layers:
            la = layer.moe.l_aux
            if la is not None:
                total = la if total is None else total + la
        return total

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        lm = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]))
        aux = self.aux_loss()
        if aux is not None:
            lm = lm + self.aux_loss_weight * aux
        return lm

    def apply_expert_placements(self, mesh, axis="dp"):
        """Expert parallelism for every MoE layer."""
        from paddle_tpu.incubate.distributed.models.moe import (
            shard_expert_parameters)

        for layer in self.model.layers:
            shard_expert_parameters(layer.moe, mesh, axis)
        return self
