"""LLaMA model family + kv-cache generation.

Capability slot: the reference trains/serves LLaMA through PaddleNLP on
Fleet hybrid parallel (BASELINE.md config 5: LLaMA-7B sharding_stage3 +
recompute). The architecture here IS the GPT family core (rmsnorm + swiglu
+ rope + GQA, models/gpt.py) with LLaMA naming, presets, and a greedy/
sampling ``generate`` loop over a kv cache.

TPU-first decode: the cache is a fixed-shape [B, max_len, H, D] buffer
updated with dynamic_update_slice, so every decode step reuses ONE
compiled program (no shape churn); attention masks the unwritten tail.
"""
from __future__ import annotations

import math

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.dispatch import apply_op

from .gpt import (GPTConfig, GPTForCausalLM, GPTForCausalLMPipe, GPTModel,
                  _rms_pure, _rope_pure)


class LlamaConfig(GPTConfig):
    def __init__(self, **kw):
        kw.setdefault("norm_type", "rmsnorm")
        kw.setdefault("act", "swiglu")
        kw.setdefault("rope", True)
        kw.setdefault("tie_embeddings", False)
        super().__init__(**kw)


def llama_preset(size="7b", **overrides):
    presets = {
        "tiny": dict(hidden_size=256, num_layers=4, num_heads=4,
                     vocab_size=1024, max_seq_len=512),
        "7b": dict(hidden_size=4096, num_layers=32, num_heads=32,
                   vocab_size=32000, max_seq_len=4096),
        "13b": dict(hidden_size=5120, num_layers=40, num_heads=40,
                    vocab_size=32000, max_seq_len=4096),
        "70b": dict(hidden_size=8192, num_layers=80, num_heads=64,
                    num_kv_heads=8, vocab_size=32000, max_seq_len=4096,
                    intermediate_size=28672),
    }
    cfg = dict(presets[size])
    cfg.update(overrides)
    return LlamaConfig(**cfg)


class LlamaModel(GPTModel):
    pass


class LlamaForCausalLM(GPTForCausalLM):
    """LLaMA decoder LM with generation."""

    def __init__(self, config=None, **kw):
        if config is None:
            config = LlamaConfig(**kw)
        super().__init__(config)

    # -- decode path -------------------------------------------------------
    def _decode_params(self):
        """Collect per-layer weights once (name -> stacked python list)."""
        layers = self.model.layers
        return [
            dict(
                ln1=l.input_norm.weight, wq=l.attn.q_proj.weight,
                wk=l.attn.k_proj.weight, wv=l.attn.v_proj.weight,
                wo=l.attn.o_proj.weight, ln2=l.post_attn_norm.weight,
                wg=l.mlp.gate_proj.weight, wu=l.mlp.up_proj.weight,
                wd=l.mlp.down_proj.weight,
            )
            for l in layers
        ]

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, seed=0, int8_weights=False):
        """Greedy (temperature=0) or sampled decode with a kv cache.

        input_ids: [B, S] Tensor/array. Returns [B, S + max_new_tokens].

        ``int8_weights=True`` requests int8-resident decode weights
        (docs/QUANT.md): the 7 projection slabs quantize once per call
        (per-output-column codes + f32 scales, quant.gemm) and every
        decode GEMM runs int8 x int8 -> int32 without dequantizing the
        weights — the same mode the serving engine packs per replica.
        Engages only behind the round-trip probe; ``PTPU_INT8_WEIGHTS``
        forces either way (``0`` is the exact escape hatch).
        """
        import jax
        import jax.numpy as jnp

        from ..quant import (int8_weight_matmul, int8_weights_enabled,
                             quantize_weight_cols_int8)

        cfg = self.config
        ids = input_ids._data if hasattr(input_ids, "_data") else jnp.asarray(
            input_ids)
        b, s0 = ids.shape
        max_len = s0 + max_new_tokens
        hd = cfg.hidden_size // cfg.num_heads
        n_layers = cfg.num_layers

        use_int8_w = int8_weights_enabled(int8_weights)
        proj = {"wq", "wk", "wv", "wo", "wg", "wu", "wd"}
        params = self._decode_params()
        flat_params = []
        for lp in params:
            for k in ("ln1", "wq", "wk", "wv", "wo", "ln2", "wg", "wu",
                      "wd"):
                w = lp[k]._data
                flat_params.append(
                    quantize_weight_cols_int8(w)
                    if use_int8_w and k in proj else w)

        def _mm(x, w):
            # exact slab -> plain GEMM; (codes, scales) -> int8 GEMM
            return (int8_weight_matmul(x, *w) if isinstance(w, tuple)
                    else x @ w)
        embed = self.model.embed_tokens.weight._data
        fnorm = self.model.final_norm.weight._data
        head = (self.lm_head.weight._data if self.lm_head is not None
                else None)

        def rope_at(x, pos):
            # x: [B, T, H, D] starting at absolute position `pos`
            d = x.shape[-1]
            t = x.shape[1]
            p = (pos + jnp.arange(t))[:, None].astype(jnp.float32)
            inv = 10000.0 ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
            freqs = p * inv[None, :]
            sin = jnp.sin(freqs)[None, :, None, :]
            cos = jnp.cos(freqs)[None, :, None, :]
            x1, x2 = x[..., : d // 2], x[..., d // 2:]
            return jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1
            ).astype(x.dtype)

        def block_step(x, lp, kcache, vcache, pos, t_new):
            """One decoder block over t_new tokens at absolute `pos`,
            updating [B, max_len, Hkv, D] caches in place."""
            ln1, wq, wk, wv, wo, ln2, wg, wu, wd = lp
            bsz, t, hdim = x.shape
            h = _rms_pure(x, ln1)
            q = _mm(h, wq).reshape(bsz, t, cfg.num_heads, hd)
            k = _mm(h, wk).reshape(bsz, t, cfg.num_kv_heads, hd)
            v = _mm(h, wv).reshape(bsz, t, cfg.num_kv_heads, hd)
            q, k = rope_at(q, pos), rope_at(k, pos)
            zero = jnp.int32(0)
            kcache = jax.lax.dynamic_update_slice(
                kcache, k.astype(kcache.dtype),
                (zero, jnp.int32(pos), zero, zero))
            vcache = jax.lax.dynamic_update_slice(
                vcache, v.astype(vcache.dtype),
                (zero, jnp.int32(pos), zero, zero))
            if cfg.num_kv_heads != cfg.num_heads:
                rep = cfg.num_heads // cfg.num_kv_heads
                ck = jnp.repeat(kcache, rep, axis=2)
                cv = jnp.repeat(vcache, rep, axis=2)
            else:
                ck, cv = kcache, vcache
            # attention over the cache with validity + causal mask
            scale = 1.0 / math.sqrt(hd)
            logits = jnp.einsum("bthd,bshd->bhts",
                                (q * scale).astype(jnp.float32),
                                ck.astype(jnp.float32))
            key_pos = jnp.arange(max_len)[None, :]
            qry_pos = pos + jnp.arange(t)[:, None]
            mask = key_pos <= qry_pos  # causal + only written slots
            logits = jnp.where(mask[None, None], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            o = jnp.einsum("bhts,bshd->bthd", probs,
                           cv.astype(jnp.float32)).astype(x.dtype)
            o = o.reshape(bsz, t, cfg.num_heads * hd)
            x = x + _mm(o, wo)
            h2 = _rms_pure(x, ln2)
            x = x + _mm(jax.nn.silu(_mm(h2, wg)) * _mm(h2, wu), wd)
            return x, kcache, vcache

        def forward_step(token_ids, caches, pos):
            """token_ids [B, T] -> (next-token logits [B, V], new caches)."""
            x = embed[token_ids]
            new_caches = []
            for li in range(n_layers):
                lp = tuple(flat_params[li * 9:(li + 1) * 9])
                kc, vc = caches[li]
                x, kc, vc = block_step(x, lp, kc, vc, pos, token_ids.shape[1])
                new_caches.append((kc, vc))
            x = _rms_pure(x, fnorm)
            last = x[:, -1]
            logits = (last @ head if head is not None
                      else last @ embed.T)
            return logits.astype(jnp.float32), new_caches

        @jax.jit
        def prefill(ids, caches):
            return forward_step(ids, caches, 0)

        @jax.jit
        def decode_one(tok, caches, pos, key):
            logits, caches = forward_step(tok, caches, pos)
            if temperature > 0.0:
                lg = logits / temperature
                if top_k > 0:
                    kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
                    lg = jnp.where(lg < kth, -jnp.inf, lg)
                nxt = jax.random.categorical(key, lg, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(ids.dtype), caches

        caches = [
            (jnp.zeros((b, max_len, cfg.num_kv_heads, hd), embed.dtype),
             jnp.zeros((b, max_len, cfg.num_kv_heads, hd), embed.dtype))
            for _ in range(n_layers)
        ]
        logits, caches = prefill(ids, caches)
        nxt = jnp.argmax(logits, axis=-1).astype(ids.dtype)
        out = [ids, nxt[:, None]]
        key = jax.random.PRNGKey(seed)
        pos = s0
        for i in range(max_new_tokens - 1):
            key, sub = jax.random.split(key)
            # pos as a traced scalar: every decode step reuses one program
            nxt, caches = decode_one(nxt[:, None], caches,
                                     jnp.int32(pos), sub)
            out.append(nxt[:, None])
            pos += 1
        return paddle.to_tensor(jnp.concatenate(out, axis=1))


class LlamaForCausalLMPipe(GPTForCausalLMPipe):
    pass
