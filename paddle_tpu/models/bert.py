"""BERT family (baseline config 3: BERT-base pretraining, dp + AMP O2).

Capability slot: the reference trains BERT through PaddleNLP; the layer
inventory here (learned embeddings + post-LN transformer encoder + MLM/NSP
heads) matches that architecture built from paddle_tpu.nn layers so the
whole step compiles to one XLA program.
"""
from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=3072, max_seq_len=512,
                 type_vocab_size=2, dropout=0.1, dtype="float32"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.dtype = dtype


def bert_base(**overrides):
    return BertConfig(**overrides)


def bert_large(**overrides):
    cfg = dict(hidden_size=1024, num_layers=24, num_heads=16,
               intermediate_size=4096)
    cfg.update(overrides)
    return BertConfig(**cfg)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = paddle.arange(s)
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertEncoderLayer(nn.Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.q = nn.Linear(h, h)
        self.k = nn.Linear(h, h)
        self.v = nn.Linear(h, h)
        self.out = nn.Linear(h, h)
        self.attn_norm = nn.LayerNorm(h)
        self.fc1 = nn.Linear(h, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, h)
        self.ffn_norm = nn.LayerNorm(h)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        hd = h // self.num_heads

        def split(t):
            return t.reshape([b, s, self.num_heads, hd])

        attn = F.scaled_dot_product_attention(
            split(self.q(x)), split(self.k(x)), split(self.v(x)),
            attn_mask=attn_mask, dropout_p=0.0, is_causal=False,
            training=self.training,
        ).reshape([b, s, h])
        x = self.attn_norm(x + self.dropout(self.out(attn)))
        ffn = self.fc2(F.gelu(self.fc1(x)))
        return self.ffn_norm(x + self.dropout(ffn))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = nn.LayerList(
            [BertEncoderLayer(cfg) for _ in range(cfg.num_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        mask = None
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            mask = (attention_mask.astype("float32") - 1.0) * 1e9
            mask = mask.reshape([x.shape[0], 1, 1, x.shape[1]])
        for layer in self.layers:
            x = layer(x, mask)
        pooled = paddle.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (BERT pretraining objective)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.config = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        mlm_logits = paddle.matmul(
            h, self.bert.embeddings.word_embeddings.weight, transpose_y=True)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits

    def loss(self, input_ids, mlm_labels, nsp_labels=None,
             token_type_ids=None, attention_mask=None, ignore_index=-100):
        mlm_logits, nsp_logits = self(input_ids, token_type_ids,
                                      attention_mask)
        v = self.config.vocab_size
        flat_logits = mlm_logits.reshape([-1, v])
        flat_labels = mlm_labels.reshape([-1])
        mask = (flat_labels != ignore_index).astype("float32")
        safe_labels = paddle.where(
            flat_labels == ignore_index,
            paddle.zeros_like(flat_labels), flat_labels)
        per_tok = F.cross_entropy(flat_logits, safe_labels, reduction="none")
        mlm_loss = (per_tok.reshape([-1]) * mask).sum() / mask.sum().clip(min=1.0)
        if nsp_labels is None:
            return mlm_loss
        nsp_loss = F.cross_entropy(nsp_logits, nsp_labels.reshape([-1]))
        return mlm_loss + nsp_loss
