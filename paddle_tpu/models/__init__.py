"""Flagship model implementations (GPT / LLaMA / BERT) used by benchmarks
and the driver entrypoints. Vision models live in paddle_tpu.vision.models."""
