"""Global FLAGS registry (parity: paddle/common/flags.h PD_DEFINE_* + python
get_flags/set_flags).

Flags are registered with type + default + help, can be set from env
(``FLAGS_name``) or programmatically. A future native (C++) registry can slot
in behind the same API (reference keeps flags in C++ for zero-overhead reads;
here reads are python-side config lookups, not in the hot path because XLA
compiles the hot path).
"""
from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_registry = {}


class Flag:
    __slots__ = ("name", "default", "value", "type", "help")

    def __init__(self, name, default, help=""):
        self.name = name
        self.default = default
        self.value = self._from_env(name, default)
        self.type = type(default)
        self.help = help

    @staticmethod
    def _from_env(name, default):
        env = os.environ.get(f"FLAGS_{name}")
        if env is None:
            return default
        if isinstance(default, bool):
            return env.lower() in ("1", "true", "yes")
        if isinstance(default, int):
            return int(env)
        if isinstance(default, float):
            return float(env)
        return env


def define_flag(name, default, help=""):
    with _lock:
        if name not in _registry:
            _registry[name] = Flag(name, default, help)
    return _registry[name]


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for f in flags:
        key = f[6:] if f.startswith("FLAGS_") else f
        if key in _registry:
            out[f] = _registry[key].value
        else:
            env = os.environ.get(f if f.startswith("FLAGS_") else f"FLAGS_{f}")
            out[f] = env
    return out


def set_flags(flags_dict):
    for k, v in flags_dict.items():
        key = k[6:] if k.startswith("FLAGS_") else k
        with _lock:
            if key not in _registry:
                _registry[key] = Flag(key, v)
            else:
                _registry[key].value = v


# core flags mirrored from paddle/common/flags.cc (subset relevant on TPU)
define_flag("check_nan_inf", False, "check nan/inf after every op (debug)")
define_flag("benchmark", False, "synchronous timing mode")
define_flag("use_pallas_kernels", True, "use Pallas kernels for fused ops on TPU")
define_flag("allocator_strategy", "xla", "memory allocator strategy (XLA-managed)")
define_flag("tpu_matmul_precision", "default", "jax matmul precision")
define_flag("spmd_rule_constraints", True,
            "insert per-op spmd-rule sharding constraints (embedding/"
            "attention/moe) when a hybrid mesh is active")
