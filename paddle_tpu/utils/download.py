"""paddle.utils.download — weights fetch/cache/integrity layer.

Parity: python/paddle/utils/download.py (WEIGHTS_HOME:59,
get_weights_path_from_url:73, get_path_from_url:119, _md5check). The
vision model zoo's ``pretrained=`` flows through here
(reference vision/models/resnet.py:20 get_weights_path_from_url).

TPU-environment notes: the cache layout and integrity checks are
identical to the reference's; the transport accepts ``file://`` URLs and
plain local paths in addition to http(s), so air-gapped hosts populate
``WEIGHTS_HOME`` out of band and every ``pretrained=True`` call resolves
locally. A missing file NEVER falls back to random init — it raises.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import shutil
import tempfile

WEIGHTS_HOME = osp.expanduser(
    os.environ.get("PTPU_WEIGHTS_HOME", "~/.cache/paddle_tpu/hapi/weights"))

__all__ = ["WEIGHTS_HOME", "get_weights_path_from_url", "get_path_from_url"]


def _md5check(fullpath, md5sum=None):
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(fullpath, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def _cache_name(url):
    """Cache key: basename + a short url hash — two sources sharing a
    filename must not alias to one cache entry (a stale-read trap when
    no md5 is given)."""
    base = osp.basename(url.split("?")[0]) or "weights"
    if "://" not in url:
        url = osp.abspath(url)
    tag = hashlib.sha1(url.encode()).hexdigest()[:10]
    root, ext = osp.splitext(base)
    return f"{root}.{tag}{ext}"


def _download(url, root_dir):
    """Fetch `url` into root_dir atomically (tmp file + rename)."""
    os.makedirs(root_dir, exist_ok=True)
    fullpath = osp.join(root_dir, _cache_name(url))
    src = None
    if url.startswith("file://"):
        src = url[len("file://"):]
    elif "://" not in url:  # plain local path
        src = url
    if src is not None and not osp.exists(src):
        raise FileNotFoundError(f"local weights path not found: {src}")
    fd, tmp = tempfile.mkstemp(dir=root_dir)
    os.close(fd)
    try:
        if src is not None:
            shutil.copyfile(src, tmp)
        else:
            import urllib.request

            with urllib.request.urlopen(url) as r, open(tmp, "wb") as out:
                shutil.copyfileobj(r, out)
        os.replace(tmp, fullpath)  # atomic: no torn cache entry, ever
    except Exception:
        if osp.exists(tmp):
            os.remove(tmp)
        raise
    return fullpath


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True):
    """Cache-or-fetch: return the local path for `url` under root_dir,
    verifying the md5 when given (re-fetches on mismatch).

    Lookup order: (1) a PLAIN-basename file in root_dir — the air-gapped
    pre-population contract ("drop resnet18.pdparams into WEIGHTS_HOME");
    (2) the url-hash-keyed cache entry this module writes on fetch (two
    sources sharing a basename must not alias); (3) fetch."""
    base = osp.basename(url.split("?")[0]) or "weights"
    prepop = osp.join(root_dir, base)
    if check_exist and osp.exists(prepop) and _md5check(prepop, md5sum):
        return prepop
    fullpath = osp.join(root_dir, _cache_name(url))
    if check_exist and osp.exists(fullpath) and _md5check(fullpath, md5sum):
        return fullpath
    fullpath = _download(url, root_dir)
    if not _md5check(fullpath, md5sum):
        os.remove(fullpath)
        raise RuntimeError(
            f"md5 mismatch for {url}: the downloaded/copied file is "
            "corrupt (removed from cache)")
    return fullpath


def get_weights_path_from_url(url, md5sum=None):
    """Resolve a weights URL through the WEIGHTS_HOME cache."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
