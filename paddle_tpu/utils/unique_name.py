"""Unique name generator (parity: python/paddle/utils/unique_name)."""
from __future__ import annotations

import collections
import contextlib

_counters = collections.defaultdict(int)


def generate(key):
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


@contextlib.contextmanager
def guard(new_generator=None):
    global _counters
    old = _counters
    _counters = collections.defaultdict(int)
    try:
        yield
    finally:
        _counters = old


def switch(new_generator=None):
    global _counters
    _counters = collections.defaultdict(int)
