"""paddle.utils.cpp_extension — runtime-compiled custom C++ ops.

Capability parity: `python/paddle/utils/cpp_extension/` (`load` :895,
`setup` :92) + the custom-operator runtime (`fluid/framework/
custom_operator.cc`, which compiles REAL device kernels). Two tiers:

- **Device-kernel path** (`get_ffi_op`, r4): the C++ source implements an
  XLA FFI handler (`xla/ffi/api/ffi.h`, headers shipped with jaxlib —
  compile with ``load(..., with_ffi=True)``). The handler registers as a
  custom-call target and executes INSIDE the compiled XLA program on the
  CPU backend — jit-compatible, no host round-trip, the N38 parity slot
  (`fluid/framework/custom_operator.cc` kernels inside the executor).
  TPU device kernels route through Pallas (`paddle_tpu.ops.pallas`) —
  the chip's only user-programmable kernel surface.
- **Host path** (`get_op`): plain C ABI bridged with ``jax.pure_callback``
  (host compute seam). C ABI: ``extern "C" void <op>(const float* x,
  float* y, int64_t n)``; richer signatures via ``module.lib.<symbol>``.

Binding is ctypes (no pybind11 in this environment).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension has no TPU equivalent — write device kernels as "
        "Pallas kernels (paddle_tpu.ops.pallas) and host compute as "
        "CppExtension"
    )


class CppExtensionModule:
    """Loaded custom-op library."""

    def __init__(self, name, lib_path):
        self.name = name
        self.lib_path = lib_path
        self.lib = ctypes.CDLL(lib_path)

    def get_op(self, symbol, dtype=np.float32):
        """Wrap `extern "C" void f(const T*, T*, int64)` as a framework op
        usable eagerly AND inside jit (via pure_callback)."""
        import jax
        import jax.numpy as jnp

        from ..core.dispatch import apply_op

        cfn = getattr(self.lib, symbol)
        cdt = np.ctypeslib.ndpointer(dtype=dtype, flags="C_CONTIGUOUS")
        cfn.argtypes = [cdt, cdt, ctypes.c_int64]
        cfn.restype = None

        def host_call(x):
            x = np.ascontiguousarray(np.asarray(x, dtype))
            out = np.empty_like(x)
            cfn(x.reshape(-1), out.reshape(-1), x.size)
            return out

        def op(x):
            def _f(xa):
                return jax.pure_callback(
                    host_call,
                    jax.ShapeDtypeStruct(xa.shape, dtype),
                    xa,
                )

            return apply_op(_f, x, _op_name=symbol)

        op.__name__ = symbol
        return op

    def get_ffi_op(self, symbol, dtype=np.float32):
        """Wrap an XLA FFI handler symbol as a framework op whose kernel
        runs INSIDE the compiled program (custom-call, not host
        callback) — the device-kernel custom-op path on the CPU backend
        (N38: fluid/framework/custom_operator.cc executes user kernels
        in the executor; here the executor is XLA)."""
        import jax
        import jax.ffi as jffi

        from ..core.dispatch import apply_op

        target = f"ptpu_{self.name}_{symbol}"
        if target not in _FFI_REGISTERED:
            handler = getattr(self.lib, symbol)
            jffi.register_ffi_target(target, jffi.pycapsule(handler),
                                     platform="cpu")
            _FFI_REGISTERED.add(target)

        def op(x):
            def _f(xa):
                call = jax.ffi.ffi_call(
                    target, jax.ShapeDtypeStruct(xa.shape, dtype))
                return call(xa)

            return apply_op(_f, x, _op_name=symbol)

        op.__name__ = symbol
        return op


_FFI_REGISTERED: set = set()


def load(name, sources, extra_cxx_cflags=None, extra_cflags=None,
         extra_ldflags=None, build_directory=None, verbose=False,
         with_ffi=False, **kwargs):
    """Compile `sources` and load the library (cpp_extension.py:895).

    ``with_ffi=True`` adds jaxlib's XLA FFI include root so sources can
    implement custom-call handlers (see get_ffi_op)."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    lib_path = os.path.join(build_dir, f"lib{name}.so")
    srcs = [str(s) for s in sources]
    newest = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(lib_path) or os.path.getmtime(lib_path) < newest:
        inc = []
        if with_ffi:
            import jax.ffi as jffi

            inc = ["-I", jffi.include_dir()]
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"] + inc
               + (extra_cxx_cflags or extra_cflags or [])
               + ["-o", lib_path] + srcs + (extra_ldflags or []))
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose,
                       timeout=300)
    return CppExtensionModule(name, lib_path)


def setup(name=None, ext_modules=None, **kwargs):
    """setuptools-style build: compiles every CppExtension now."""
    mods = []
    for ext in (ext_modules or []):
        if isinstance(ext, CppExtension):
            mods.append(load(name or "custom", ext.sources))
    return mods


def get_build_directory():
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")
