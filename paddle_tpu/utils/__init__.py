"""paddle.utils (parity subset: flags, unique_name, deprecated helpers)."""
from . import download  # noqa: F401
from . import flags  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required but not installed")


def run_check():
    import jax

    import paddle_tpu as paddle

    x = paddle.ones([2, 2])
    y = paddle.matmul(x, x)
    assert float(y.sum().item()) == 8.0
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! devices: {devs}")


class deprecated:
    def __init__(self, update_to="", since="", reason="", level=0):
        pass

    def __call__(self, fn):
        return fn


def require_version(min_version, max_version=None):
    """parity: utils/__init__ require_version — checks framework version."""
    return True
