"""Mesh/topology/sharding machinery backing paddle_tpu.distributed.

The user-facing API lives in paddle_tpu.distributed; this package holds the
TPU-native internals (global mesh management, axis topology, sharding specs).
"""
