"""paddle_tpu — a TPU-native deep-learning framework.

Capability parity with PaddlePaddle's public API surface
(``python/paddle/__init__.py``), built from scratch on jax/XLA/Pallas:
eager ops dispatch to XLA (debug path), training loops compile through
``jax.jit``/pjit (perf path), parallelism maps onto ``jax.sharding.Mesh``.
"""
from __future__ import annotations

import jax as _jax

from . import _jax_compat as _jax_compat_module

_jax_compat_module.install()

# float64 capability parity with the reference (x64 must be on before tracing)
_jax.config.update("jax_enable_x64", True)
# keep python-float default at float32 (paddle semantics) via weak types.

from . import dtypes as _dtype_module
from .dtypes import (  # noqa: F401
    DType,
    bool_,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    float8_e4m3fn,
    float8_e5m2,
    complex64,
    complex128,
    iinfo,
    finfo,
    promote_types,
)

dtype = DType  # paddle.dtype is the dtype class

from .device import (  # noqa: F401
    Place,
    TPUPlace,
    CPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    XPUPlace,
    CustomPlace,
    set_device,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_rocm,
    is_compiled_with_xpu,
    is_compiled_with_tpu,
)

from .framework import (  # noqa: F401
    no_grad,
    enable_grad,
    set_grad_enabled,
    is_grad_enabled,
    set_default_dtype,
    get_default_dtype,
    seed,
    get_rng_state,
    set_rng_state,
    in_dynamic_mode,
    in_dynamic_or_pir_mode,
    Generator,
)

from .core.tensor import Tensor, Parameter  # noqa: F401

# ops: importing patches Tensor methods
from .ops import *  # noqa: F401,F403
from . import ops as _ops

from .autograd import grad, PyLayer  # noqa: F401

# numeric constants (parity: paddle.pi / e / inf / nan / newaxis)
import math as _math

import numpy as _np_mod

bool = _np_mod.bool_  # paddle.bool dtype alias (shadows builtins.bool here only)
pstring = "pstring"   # string-tensor dtype tag (reference: phi StringTensor)
raw = "raw"           # raw dtype tag (reference: DataType::UNDEFINED carrier)

pi = _math.pi
e = _math.e
inf = float("inf")
nan = float("nan")
newaxis = None


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def disable_signal_handler():
    pass


def check_shape(tensor):
    return list(tensor.shape)


def get_cuda_rng_state():
    from . import framework as _fw

    return _fw.get_rng_state()


def set_cuda_rng_state(state):
    from . import framework as _fw

    _fw.set_rng_state(state)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .nn.layer.layers import Layer

    holder = Layer.__new__(Layer)
    Layer.__init__(holder)
    return holder.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


class LazyGuard:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched

from . import autograd  # noqa: F401

# subpackages (populated progressively; import lazily where heavy)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import device  # noqa: F401
from . import utils  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler  # noqa: F401
from . import telemetry  # noqa: F401
from . import memory  # noqa: F401
from . import static  # noqa: F401
from . import sparse  # noqa: F401
from . import strings  # noqa: F401
from . import distribution  # noqa: F401
from . import linalg_ns as linalg  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import geometric  # noqa: F401
from . import quantization  # noqa: F401
from . import autograd  # noqa: F401
from .hapi.model import Model, summary  # noqa: F401
from .framework_io import save, load  # noqa: F401
from .framework_io import async_save, clear_async_save_task_queue  # noqa: F401
from .ops.compat import to_dlpack, from_dlpack  # noqa: F401
from .distributed.data_parallel import DataParallel  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401

from . import version  # noqa: F401
from . import inference  # noqa: F401
from . import callbacks  # noqa: F401
from . import regularizer  # noqa: F401
from . import sysconfig  # noqa: F401
from . import hub  # noqa: F401
from . import api_tracer  # noqa: F401
from . import cost_model  # noqa: F401
from . import ir  # noqa: F401
from . import tensorrt  # noqa: F401

__version__ = version.full_version


def disable_static(place=None):
    return None


def enable_static():
    raise NotImplementedError(
        "paddle_tpu is dynamic-first; use paddle_tpu.jit.to_static for "
        "compiled execution."
    )


def is_grad_enabled_():
    return is_grad_enabled()


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops

    return _flops(net, input_size, custom_ops, print_detail)


def get_flags(flags):
    from .utils import flags as _flags

    return _flags.get_flags(flags)


def set_flags(flags):
    from .utils import flags as _flags

    return _flags.set_flags(flags)


def synchronize():
    """Block until all enqueued device work completes."""
    try:
        _jax.effects_barrier()
    except Exception:
        pass


class CUDAGraph:  # capability slot: jit already gives whole-step graphs on TPU
    def __init__(self, *a, **k):
        raise NotImplementedError("Use paddle_tpu.jit — XLA compiles whole-step graphs.")

from . import cinn  # noqa: F401
