"""Vision datasets (parity: python/paddle/vision/datasets).

Zero-egress environment: MNIST/Cifar load from local files when present and
raise informatively otherwise; FakeData provides synthetic samples for tests
and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset


class FakeData(Dataset):
    """Synthetic image classification dataset (deterministic per index)."""

    def __init__(self, size=1000, image_shape=(1, 28, 28), num_classes=10, transform=None, dtype="float32"):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(self.dtype)
        label = np.array(rng.randint(0, self.num_classes), dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(Dataset):
    """MNIST from local idx files (paddle layout) or synthetic fallback."""

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        if image_path and os.path.exists(image_path):
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)
        else:
            n = 60000 if mode == "train" else 10000
            fake = FakeData(size=min(n, 2048), image_shape=(28, 28))
            self.images = np.stack([fake[i][0] for i in range(len(fake))])
            self.labels = np.asarray([int(fake[i][1]) for i in range(len(fake))], np.int64)

    @staticmethod
    def _read_images(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return (data.reshape(n, rows, cols).astype(np.float32) / 255.0)

    @staticmethod
    def _read_labels(path):
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.astype(np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if img.ndim == 2:
            img = img[None]
        if self.transform is not None:
            img = self.transform(img)
        return img.astype(np.float32), np.array(self.labels[idx], np.int64)


FashionMNIST = MNIST


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        n = 1024
        fake = FakeData(size=n, image_shape=(3, 32, 32))
        self.data = [fake[i] for i in range(n)]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img, label = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """ImageFolder-style loader over class subdirectories of numpy files."""

    def __init__(self, root, loader=None, extensions=(".npy",), transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or (lambda p: np.load(p))
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.endswith(tuple(extensions)):
                    self.samples.append((os.path.join(d, fname), self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array(label, np.int64)


ImageFolder = DatasetFolder


class Flowers(Dataset):
    """Synthetic-fallback Flowers102 (zero-egress stand-in)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend=None):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.n = 128
        self.transform = transform
        self.images = [
            (rng.rand(64, 64, 3) * 255).astype(np.uint8)
            for _ in range(self.n)
        ]
        self.labels = rng.randint(0, 102, (self.n,))

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return self.n


class VOC2012(Dataset):
    """Synthetic-fallback VOC segmentation pairs."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend=None):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.n = 64
        self.transform = transform
        self.images = [
            (rng.rand(64, 64, 3) * 255).astype(np.uint8)
            for _ in range(self.n)
        ]
        self.masks = [rng.randint(0, 21, (64, 64)).astype(np.uint8)
                      for _ in range(self.n)]

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return self.n
