"""paddle.vision.ops — detection ops (parity: python/paddle/vision/ops.py).

TPU-native forms: box math is vectorised jnp; RoI align/pool use bilinear
gather (XLA lowers to dynamic-slice gathers); nms runs the classic greedy
suppression with a lax.fori loop over a fixed box budget (static shapes).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def box_area(boxes):
    return apply_op(
        lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes,
        _op_name="box_area")


def box_iou(boxes1, boxes2):
    def _iou(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)

    return apply_op(_iou, boxes1, boxes2, _op_name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS; returns kept indices sorted by score. With
    category_idxs, suppression happens within each category only."""
    if category_idxs is not None:
        cats = np.asarray(category_idxs.numpy()
                          if hasattr(category_idxs, "numpy")
                          else category_idxs)
        if categories is None:
            categories = np.unique(cats)
        sc_np = (np.asarray(scores.numpy()) if scores is not None else None)
        kept_all = []
        for c in categories:
            idx = np.where(cats == c)[0]
            if idx.size == 0:
                continue
            sub_boxes = Tensor(boxes._data[idx])
            sub_scores = (Tensor(scores._data[idx])
                          if scores is not None else None)
            sub_kept = np.asarray(
                nms(sub_boxes, iou_threshold, sub_scores).numpy())
            kept_all.append(idx[sub_kept])
        kept = np.concatenate(kept_all) if kept_all else np.array([], np.int64)
        if sc_np is not None:
            kept = kept[np.argsort(-sc_np[kept])]
        if top_k is not None:
            kept = kept[:top_k]
        return Tensor(jnp.asarray(kept))

    def _nms(bx, sc):
        n = bx.shape[0]
        if sc is None:
            sc = jnp.arange(n, 0, -1).astype(jnp.float32)
        order = jnp.argsort(-sc)
        bx_sorted = bx[order]
        area = (bx_sorted[:, 2] - bx_sorted[:, 0]) * (
            bx_sorted[:, 3] - bx_sorted[:, 1])

        def body(i, keep):
            lt = jnp.maximum(bx_sorted[i, :2], bx_sorted[:, :2])
            rb = jnp.minimum(bx_sorted[i, 2:], bx_sorted[:, 2:])
            wh = jnp.clip(rb - lt, 0)
            inter = wh[:, 0] * wh[:, 1]
            iou = inter / (area[i] + area - inter)
            suppress = (iou > iou_threshold) & (jnp.arange(n) > i)
            return jnp.where(keep[i], keep & ~suppress, keep)

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return order, keep

    order, keep = apply_op(_nms, boxes, scores, _op_name="nms")
    order_np = np.asarray(order._data)
    keep_np = np.asarray(keep._data)
    kept = order_np[keep_np]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    os_ = (output_size, output_size) if isinstance(output_size, int) else output_size

    # samples per bin edge: the reference uses ceil(bin_size) when
    # sampling_ratio<=0, which is data-dependent per box — XLA needs static
    # shapes, so the adaptive case uses the common fixed default of 2
    grid = sampling_ratio if sampling_ratio > 0 else 2

    def _ra(feat, bx, bn):
        n, c, h, w = feat.shape
        oh, ow = os_
        offset = 0.5 if aligned else 0.0
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=bx.shape[0])

        def one_box(b, bi):
            x1 = b[0] * spatial_scale - offset
            y1 = b[1] * spatial_scale - offset
            x2 = b[2] * spatial_scale - offset
            y2 = b[3] * spatial_scale - offset
            bw = x2 - x1
            bh = y2 - y1
            if not aligned:  # reference clamps unaligned rois to >= 1 pixel
                bw = jnp.maximum(bw, 1.0)
                bh = jnp.maximum(bh, 1.0)
            else:
                bw = jnp.maximum(bw, 1e-4)
                bh = jnp.maximum(bh, 1e-4)
            # sample centers: bin ph, sub-sample iy -> y1 + (ph + (iy+.5)/g)*bh/oh
            sub = (jnp.arange(grid) + 0.5) / grid
            ys = y1 + (jnp.arange(oh)[:, None] + sub[None, :]) * bh / oh
            xs = x1 + (jnp.arange(ow)[:, None] + sub[None, :]) * bw / ow
            yy = jnp.broadcast_to(ys[:, :, None, None], (oh, grid, ow, grid))
            xx = jnp.broadcast_to(xs[None, None, :, :], (oh, grid, ow, grid))
            # reference bilinear rule: samples outside [-1, H/W] contribute 0
            valid = ((yy >= -1.0) & (yy <= h) & (xx >= -1.0) & (xx <= w))
            yc = jnp.clip(yy, 0.0, h - 1)
            xc = jnp.clip(xx, 0.0, w - 1)
            y0 = jnp.floor(yc).astype(jnp.int32)
            x0 = jnp.floor(xc).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = yc - y0
            wx = xc - x0
            fm = feat[bi]  # [C, H, W]
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1i]
            v10 = fm[:, y1i, x0]
            v11 = fm[:, y1i, x1i]
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
            val = jnp.where(valid[None], val, 0.0)
            return val.mean(axis=(2, 4))  # average the grid x grid samples

        return jax.vmap(one_box)(bx, batch_idx)

    return apply_op(_ra, x, boxes, boxes_num, _op_name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    os_ = (output_size, output_size) if isinstance(output_size, int) else output_size

    def _rp(feat, bx, bn):
        n, c, h, w = feat.shape
        oh, ow = os_
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=bx.shape[0])

        def one_box(b, bi):
            x1 = jnp.floor(b[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.floor(b[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.ceil(b[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.ceil(b[3] * spatial_scale).astype(jnp.int32)
            bh = jnp.maximum(y2 - y1, 1)
            bw = jnp.maximum(x2 - x1, 1)
            # 2x2 samples per output cell, max-pooled
            gy = jnp.clip(y1 + (jnp.arange(oh * 2) * bh / (oh * 2))
                          .astype(jnp.int32), 0, h - 1)
            gx = jnp.clip(x1 + (jnp.arange(ow * 2) * bw / (ow * 2))
                          .astype(jnp.int32), 0, w - 1)
            fm = feat[bi][:, gy][:, :, gx]  # [C, oh*2, ow*2]
            fm = fm.reshape(c, oh, 2, ow, 2)
            return jnp.max(fm, axis=(2, 4))

        return jax.vmap(one_box)(bx, batch_idx)

    return apply_op(_rp, x, boxes, boxes_num, _op_name="roi_pool")


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling: channel group (i,j) feeds output
    cell (i,j)."""
    k = output_size if isinstance(output_size, int) else output_size[0]
    pooled = roi_align(x, boxes, boxes_num, k, spatial_scale)

    def _ps(p):
        nb, c, oh, ow = p.shape
        out_c = c // (oh * ow)
        p = p.reshape(nb, out_c, oh, ow, oh, ow)
        ii = jnp.arange(oh)
        jj = jnp.arange(ow)
        return p[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]

    return apply_op(_ps, pooled, _op_name="psroi_pool")


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    def _yb(feat, sizes):
        n, c, h, w = feat.shape
        na = len(anchors) // 2
        anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        feat = feat.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w)[None, None, None, :]
        gy = jnp.arange(h)[None, None, :, None]
        bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / (
            w * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / (
            h * downsample_ratio)
        conf = jax.nn.sigmoid(feat[:, :, 4])
        probs = jax.nn.sigmoid(feat[:, :, 5:]) * conf[:, :, None]
        img_h = sizes[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = sizes[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
        keep = (conf.reshape(n, -1) > conf_thresh)[..., None]
        return boxes * keep, scores * keep

    return apply_op(_yb, x, img_size, _op_name="yolo_box")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 via bilinear sampling + einsum contraction.

    Sampling grid per output position is shifted by the learned offsets
    (and modulated by `mask` for v2); the contraction is a single MXU
    einsum. deformable_groups == 1 supported.
    """
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if deformable_groups != 1:
        raise NotImplementedError("deformable_groups > 1")

    def _dc(xa, off, w, b, m):
        n, cin, h, win_ = xa.shape
        cout, cin_g, kh, kw = w.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (win_ + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xa_p = jnp.pad(xa, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        hp, wp = xa_p.shape[2], xa_p.shape[3]
        base_y = (jnp.arange(oh)[:, None, None, None] * st[0]
                  + jnp.arange(kh)[None, None, :, None] * dl[0])
        base_x = (jnp.arange(ow)[None, :, None, None] * st[1]
                  + jnp.arange(kw)[None, None, None, :] * dl[1])
        # offset layout [N, kh*kw*2, oh, ow] with (dy, dx) pairs per tap
        offr = off.reshape(n, kh, kw, 2, oh, ow)
        dy = jnp.transpose(offr[:, :, :, 0], (0, 3, 4, 1, 2))  # [N,oh,ow,kh,kw]
        dx = jnp.transpose(offr[:, :, :, 1], (0, 3, 4, 1, 2))
        sy = base_y[None] + dy
        sx = base_x[None] + dx
        y0 = jnp.clip(jnp.floor(sy), 0, hp - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(sx), 0, wp - 1).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, hp - 1)
        x1 = jnp.clip(x0 + 1, 0, wp - 1)
        wy = jnp.clip(sy, 0, hp - 1) - y0
        wx = jnp.clip(sx, 0, wp - 1) - x0

        def per_image(img, y0i, x0i, y1i, x1i, wyi, wxi, mi):
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            val = (v00 * (1 - wyi) * (1 - wxi) + v01 * (1 - wyi) * wxi
                   + v10 * wyi * (1 - wxi) + v11 * wyi * wxi)
            if mi is not None:
                val = val * mi[None]
            # val: [cin, oh, ow, kh, kw]
            return jnp.einsum("cijkl,ockl->oij", val, w)

        if m is not None:
            mr = m.reshape(n, kh, kw, oh, ow)
            mr = jnp.transpose(mr, (0, 3, 4, 1, 2))  # [N, oh, ow, kh, kw]
        out = jax.vmap(per_image)(
            xa_p, y0, x0, y1, x1, wy, wx,
            mr if m is not None else jnp.ones((n, oh, ow, kh, kw), xa.dtype))
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    return apply_op(_dc, x, offset, weight, bias, mask,
                    _op_name="deform_conv2d")


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        self.args = (stride, padding, dilation, deformable_groups, groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]])
        self.bias = (None if bias_attr is False
                     else self.create_parameter([out_channels], is_bias=True))

    def forward(self, x, offset, mask=None):
        st, pd, dl, dg, g = self.args
        return deform_conv2d(x, offset, self.weight, self.bias, st, pd, dl,
                             dg, g, mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    rois = np.asarray(fpn_rois.numpy() if hasattr(fpn_rois, "numpy")
                      else fpn_rois)
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, index = [], []
    for l in range(min_level, max_level + 1):
        idx = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        index.append(idx)
    restore = (np.argsort(np.concatenate(index)) if index
               else np.array([], np.int64))
    return (outs,
            [Tensor(jnp.asarray(np.asarray([len(i)], np.int32)))
             for i in index],
            Tensor(jnp.asarray(restore.astype(np.int32))))


def yolo_loss(*args, **kwargs):
    raise NotImplementedError(
        "yolo_loss: compose from yolo_box + standard losses; the fused "
        "CUDA loss has no single TPU kernel equivalent yet")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError(
        "generate_proposals: compose box decoding + nms; end-to-end RPN "
        "proposals land with the detection model family")
