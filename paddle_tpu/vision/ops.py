"""paddle.vision.ops — detection ops (parity: python/paddle/vision/ops.py).

TPU-native forms: box math is vectorised jnp; RoI align/pool use bilinear
gather (XLA lowers to dynamic-slice gathers); nms runs the classic greedy
suppression with a lax.fori loop over a fixed box budget (static shapes).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def box_area(boxes):
    return apply_op(
        lambda b: (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), boxes,
        _op_name="box_area")


def box_iou(boxes1, boxes2):
    def _iou(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter)

    return apply_op(_iou, boxes1, boxes2, _op_name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS; returns kept indices sorted by score. With
    category_idxs, suppression happens within each category only."""
    if category_idxs is not None:
        cats = np.asarray(category_idxs.numpy()
                          if hasattr(category_idxs, "numpy")
                          else category_idxs)
        if categories is None:
            categories = np.unique(cats)
        sc_np = (np.asarray(scores.numpy()) if scores is not None else None)
        kept_all = []
        for c in categories:
            idx = np.where(cats == c)[0]
            if idx.size == 0:
                continue
            sub_boxes = Tensor(boxes._data[idx])
            sub_scores = (Tensor(scores._data[idx])
                          if scores is not None else None)
            sub_kept = np.asarray(
                nms(sub_boxes, iou_threshold, sub_scores).numpy())
            kept_all.append(idx[sub_kept])
        kept = np.concatenate(kept_all) if kept_all else np.array([], np.int64)
        if sc_np is not None:
            kept = kept[np.argsort(-sc_np[kept])]
        if top_k is not None:
            kept = kept[:top_k]
        return Tensor(jnp.asarray(kept))

    def _nms(bx, sc):
        n = bx.shape[0]
        if sc is None:
            sc = jnp.arange(n, 0, -1).astype(jnp.float32)
        order = jnp.argsort(-sc)
        bx_sorted = bx[order]
        area = (bx_sorted[:, 2] - bx_sorted[:, 0]) * (
            bx_sorted[:, 3] - bx_sorted[:, 1])

        def body(i, keep):
            lt = jnp.maximum(bx_sorted[i, :2], bx_sorted[:, :2])
            rb = jnp.minimum(bx_sorted[i, 2:], bx_sorted[:, 2:])
            wh = jnp.clip(rb - lt, 0)
            inter = wh[:, 0] * wh[:, 1]
            iou = inter / (area[i] + area - inter)
            suppress = (iou > iou_threshold) & (jnp.arange(n) > i)
            return jnp.where(keep[i], keep & ~suppress, keep)

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        return order, keep

    order, keep = apply_op(_nms, boxes, scores, _op_name="nms")
    order_np = np.asarray(order._data)
    keep_np = np.asarray(keep._data)
    kept = order_np[keep_np]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    os_ = (output_size, output_size) if isinstance(output_size, int) else output_size

    # samples per bin edge: the reference uses ceil(bin_size) when
    # sampling_ratio<=0, which is data-dependent per box — XLA needs static
    # shapes, so the adaptive case uses the common fixed default of 2
    grid = sampling_ratio if sampling_ratio > 0 else 2

    def _ra(feat, bx, bn):
        n, c, h, w = feat.shape
        oh, ow = os_
        offset = 0.5 if aligned else 0.0
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=bx.shape[0])

        def one_box(b, bi):
            x1 = b[0] * spatial_scale - offset
            y1 = b[1] * spatial_scale - offset
            x2 = b[2] * spatial_scale - offset
            y2 = b[3] * spatial_scale - offset
            bw = x2 - x1
            bh = y2 - y1
            if not aligned:  # reference clamps unaligned rois to >= 1 pixel
                bw = jnp.maximum(bw, 1.0)
                bh = jnp.maximum(bh, 1.0)
            else:
                bw = jnp.maximum(bw, 1e-4)
                bh = jnp.maximum(bh, 1e-4)
            # sample centers: bin ph, sub-sample iy -> y1 + (ph + (iy+.5)/g)*bh/oh
            sub = (jnp.arange(grid) + 0.5) / grid
            ys = y1 + (jnp.arange(oh)[:, None] + sub[None, :]) * bh / oh
            xs = x1 + (jnp.arange(ow)[:, None] + sub[None, :]) * bw / ow
            yy = jnp.broadcast_to(ys[:, :, None, None], (oh, grid, ow, grid))
            xx = jnp.broadcast_to(xs[None, None, :, :], (oh, grid, ow, grid))
            # reference bilinear rule: samples outside [-1, H/W] contribute 0
            valid = ((yy >= -1.0) & (yy <= h) & (xx >= -1.0) & (xx <= w))
            yc = jnp.clip(yy, 0.0, h - 1)
            xc = jnp.clip(xx, 0.0, w - 1)
            y0 = jnp.floor(yc).astype(jnp.int32)
            x0 = jnp.floor(xc).astype(jnp.int32)
            y1i = jnp.clip(y0 + 1, 0, h - 1)
            x1i = jnp.clip(x0 + 1, 0, w - 1)
            wy = yc - y0
            wx = xc - x0
            fm = feat[bi]  # [C, H, W]
            v00 = fm[:, y0, x0]
            v01 = fm[:, y0, x1i]
            v10 = fm[:, y1i, x0]
            v11 = fm[:, y1i, x1i]
            val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                   + v10 * wy * (1 - wx) + v11 * wy * wx)
            val = jnp.where(valid[None], val, 0.0)
            return val.mean(axis=(2, 4))  # average the grid x grid samples

        return jax.vmap(one_box)(bx, batch_idx)

    return apply_op(_ra, x, boxes, boxes_num, _op_name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    os_ = (output_size, output_size) if isinstance(output_size, int) else output_size

    def _rp(feat, bx, bn):
        n, c, h, w = feat.shape
        oh, ow = os_
        batch_idx = jnp.repeat(jnp.arange(bn.shape[0]), bn,
                               total_repeat_length=bx.shape[0])

        def one_box(b, bi):
            x1 = jnp.floor(b[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.floor(b[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.ceil(b[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.ceil(b[3] * spatial_scale).astype(jnp.int32)
            bh = jnp.maximum(y2 - y1, 1)
            bw = jnp.maximum(x2 - x1, 1)
            # 2x2 samples per output cell, max-pooled
            gy = jnp.clip(y1 + (jnp.arange(oh * 2) * bh / (oh * 2))
                          .astype(jnp.int32), 0, h - 1)
            gx = jnp.clip(x1 + (jnp.arange(ow * 2) * bw / (ow * 2))
                          .astype(jnp.int32), 0, w - 1)
            fm = feat[bi][:, gy][:, :, gx]  # [C, oh*2, ow*2]
            fm = fm.reshape(c, oh, 2, ow, 2)
            return jnp.max(fm, axis=(2, 4))

        return jax.vmap(one_box)(bx, batch_idx)

    return apply_op(_rp, x, boxes, boxes_num, _op_name="roi_pool")


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling: channel group (i,j) feeds output
    cell (i,j)."""
    k = output_size if isinstance(output_size, int) else output_size[0]
    pooled = roi_align(x, boxes, boxes_num, k, spatial_scale)

    def _ps(p):
        nb, c, oh, ow = p.shape
        out_c = c // (oh * ow)
        p = p.reshape(nb, out_c, oh, ow, oh, ow)
        ii = jnp.arange(oh)
        jj = jnp.arange(ow)
        return p[:, :, ii[:, None], jj[None, :], ii[:, None], jj[None, :]]

    return apply_op(_ps, pooled, _op_name="psroi_pool")


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    def _yb(feat, sizes):
        n, c, h, w = feat.shape
        na = len(anchors) // 2
        anc = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
        feat = feat.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w)[None, None, None, :]
        gy = jnp.arange(h)[None, None, :, None]
        bx = (jax.nn.sigmoid(feat[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx) / w
        by = (jax.nn.sigmoid(feat[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy) / h
        bw = jnp.exp(feat[:, :, 2]) * anc[None, :, 0, None, None] / (
            w * downsample_ratio)
        bh = jnp.exp(feat[:, :, 3]) * anc[None, :, 1, None, None] / (
            h * downsample_ratio)
        conf = jax.nn.sigmoid(feat[:, :, 4])
        probs = jax.nn.sigmoid(feat[:, :, 5:]) * conf[:, :, None]
        img_h = sizes[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = sizes[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
        scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
        keep = (conf.reshape(n, -1) > conf_thresh)[..., None]
        return boxes * keep, scores * keep

    return apply_op(_yb, x, img_size, _op_name="yolo_box")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 via bilinear sampling + einsum contraction.

    Sampling grid per output position is shifted by the learned offsets
    (and modulated by `mask` for v2); the contraction is a single MXU
    einsum. deformable_groups == 1 supported.
    """
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dl = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    if deformable_groups != 1:
        raise NotImplementedError("deformable_groups > 1")

    def _dc(xa, off, w, b, m):
        n, cin, h, win_ = xa.shape
        cout, cin_g, kh, kw = w.shape
        oh = (h + 2 * pd[0] - dl[0] * (kh - 1) - 1) // st[0] + 1
        ow = (win_ + 2 * pd[1] - dl[1] * (kw - 1) - 1) // st[1] + 1
        xa_p = jnp.pad(xa, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
        hp, wp = xa_p.shape[2], xa_p.shape[3]
        base_y = (jnp.arange(oh)[:, None, None, None] * st[0]
                  + jnp.arange(kh)[None, None, :, None] * dl[0])
        base_x = (jnp.arange(ow)[None, :, None, None] * st[1]
                  + jnp.arange(kw)[None, None, None, :] * dl[1])
        # offset layout [N, kh*kw*2, oh, ow] with (dy, dx) pairs per tap
        offr = off.reshape(n, kh, kw, 2, oh, ow)
        dy = jnp.transpose(offr[:, :, :, 0], (0, 3, 4, 1, 2))  # [N,oh,ow,kh,kw]
        dx = jnp.transpose(offr[:, :, :, 1], (0, 3, 4, 1, 2))
        sy = base_y[None] + dy
        sx = base_x[None] + dx
        y0 = jnp.clip(jnp.floor(sy), 0, hp - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(sx), 0, wp - 1).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, hp - 1)
        x1 = jnp.clip(x0 + 1, 0, wp - 1)
        wy = jnp.clip(sy, 0, hp - 1) - y0
        wx = jnp.clip(sx, 0, wp - 1) - x0

        def per_image(img, y0i, x0i, y1i, x1i, wyi, wxi, mi):
            v00 = img[:, y0i, x0i]
            v01 = img[:, y0i, x1i]
            v10 = img[:, y1i, x0i]
            v11 = img[:, y1i, x1i]
            val = (v00 * (1 - wyi) * (1 - wxi) + v01 * (1 - wyi) * wxi
                   + v10 * wyi * (1 - wxi) + v11 * wyi * wxi)
            if mi is not None:
                val = val * mi[None]
            # val: [cin, oh, ow, kh, kw]
            return jnp.einsum("cijkl,ockl->oij", val, w)

        if m is not None:
            mr = m.reshape(n, kh, kw, oh, ow)
            mr = jnp.transpose(mr, (0, 3, 4, 1, 2))  # [N, oh, ow, kh, kw]
        out = jax.vmap(per_image)(
            xa_p, y0, x0, y1, x1, wy, wx,
            mr if m is not None else jnp.ones((n, oh, ow, kh, kw), xa.dtype))
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    return apply_op(_dc, x, offset, weight, bias, mask,
                    _op_name="deform_conv2d")


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else kernel_size
        self.args = (stride, padding, dilation, deformable_groups, groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]])
        self.bias = (None if bias_attr is False
                     else self.create_parameter([out_channels], is_bias=True))

    def forward(self, x, offset, mask=None):
        st, pd, dl, dg, g = self.args
        return deform_conv2d(x, offset, self.weight, self.bias, st, pd, dl,
                             dg, g, mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    rois = np.asarray(fpn_rois.numpy() if hasattr(fpn_rois, "numpy")
                      else fpn_rois)
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 1e-6))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(int)
    outs, index = [], []
    for l in range(min_level, max_level + 1):
        idx = np.where(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[idx])))
        index.append(idx)
    restore = (np.argsort(np.concatenate(index)) if index
               else np.array([], np.int64))
    return (outs,
            [Tensor(jnp.asarray(np.asarray([len(i)], np.int32)))
             for i in index],
            Tensor(jnp.asarray(restore.astype(np.int32))))


def yolo_loss(*args, **kwargs):
    raise NotImplementedError(
        "yolo_loss: compose from yolo_box + standard losses; the fused "
        "CUDA loss has no single TPU kernel equivalent yet")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError(
        "generate_proposals: compose box decoding + nms; end-to-end RPN "
        "proposals land with the detection model family")


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.0],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (parity: vision/ops.py:438 prior_box). Pure box
    math from the two feature-map/image shapes — vectorised jnp over the
    (H, W, num_priors) grid."""
    min_sizes = [float(s) for s in (min_sizes if isinstance(
        min_sizes, (list, tuple)) else [min_sizes])]
    if max_sizes is None:
        max_sizes = []
    elif not isinstance(max_sizes, (list, tuple)):
        max_sizes = [float(max_sizes)]
    else:
        max_sizes = [float(s) for s in max_sizes]
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError("max_sizes must match min_sizes length")
    ars = [float(a) for a in (aspect_ratios if isinstance(
        aspect_ratios, (list, tuple)) else [aspect_ratios])]
    # expand aspect ratios (reference ExpandAspectRatios + flip)
    out_ars = [1.0]
    for ar in ars:
        if all(abs(ar - e) > 1e-6 for e in out_ars):
            out_ars.append(ar)
            if flip:
                out_ars.append(1.0 / ar)
    var = [float(v) for v in (variance if isinstance(
        variance, (list, tuple)) else [variance] * 4)]

    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    # per-cell prior (w, h) list in the reference's emission order
    whs = []
    for mi, ms in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                whs.append((math.sqrt(ms * max_sizes[mi]),) * 2)
            for ar in out_ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in out_ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                whs.append((math.sqrt(ms * max_sizes[mi]),) * 2)
    wh = jnp.asarray(whs, jnp.float32)  # [P, 2]

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    c = jnp.stack([cxg, cyg], -1)[:, :, None, :]  # [H, W, 1, 2]
    half = wh[None, None, :, :] / 2.0
    mins = (c - half) / jnp.asarray([iw, ih], jnp.float32)
    maxs = (c + half) / jnp.asarray([iw, ih], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], -1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32), boxes.shape)
    return Tensor(boxes), Tensor(variances)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode detection boxes against priors (parity:
    vision/ops.py box_coder)."""
    def _coder(pb, pbv, tb):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2.0
        pcy = pb[:, 1] + ph / 2.0
        if pbv is None:
            vx = vy = vw = vh = 1.0
        elif pbv.ndim == 1:
            vx, vy, vw, vh = pbv[0], pbv[1], pbv[2], pbv[3]
        else:
            vx, vy, vw, vh = pbv[:, 0], pbv[:, 1], pbv[:, 2], pbv[:, 3]
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2.0
            tcy = tb[:, 1] + th / 2.0
            # [T, P, 4]: every target encoded against every prior
            ox = ((tcx[:, None] - pcx[None, :]) / pw[None, :]) / vx
            oy = ((tcy[:, None] - pcy[None, :]) / ph[None, :]) / vy
            ow = jnp.log(tw[:, None] / pw[None, :]) / vw
            oh = jnp.log(th[:, None] / ph[None, :]) / vh
            return jnp.stack([ox, oy, ow, oh], -1)
        # decode: target_box [P, C, 4] deltas against priors along `axis`
        t = tb
        if t.ndim == 2:
            t = t[:, None, :]
        pw_, ph_, pcx_, pcy_ = (x[:, None] if axis == 0 else x[None, :]
                                for x in (pw, ph, pcx, pcy))
        if pbv is not None and pbv.ndim == 2:
            # per-prior variances follow the prior axis
            vx, vy, vw, vh = (v[:, None] if axis == 0 else v[None, :]
                              for v in (vx, vy, vw, vh))
        dcx = vx * t[..., 0] * pw_ + pcx_
        dcy = vy * t[..., 1] * ph_ + pcy_
        dw = jnp.exp(vw * t[..., 2]) * pw_
        dh = jnp.exp(vh * t[..., 3]) * ph_
        out = jnp.stack([dcx - dw / 2.0, dcy - dh / 2.0,
                         dcx + dw / 2.0 - norm, dcy + dh / 2.0 - norm], -1)
        return out if tb.ndim == 3 else out[:, 0, :]

    return apply_op(_coder, prior_box, prior_box_var, target_box,
                    _op_name="box_coder")


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2): soft suppression via the pairwise-IoU decay
    matrix instead of sequential greedy passes (parity: vision/ops.py
    matrix_nms). Host-side numpy like the reference CPU kernel."""
    bb = np.asarray(bboxes.numpy() if hasattr(bboxes, "numpy") else bboxes)
    sc = np.asarray(scores.numpy() if hasattr(scores, "numpy") else scores)
    norm = 0.0 if normalized else 1.0
    outs, inds, nums = [], [], []
    for b in range(bb.shape[0]):
        dets = []
        for c in range(sc.shape[1]):
            if c == background_label:
                continue
            keep = np.where(sc[b, c] > score_threshold)[0]
            if keep.size == 0:
                continue
            s = sc[b, c][keep]
            order = np.argsort(-s)
            if nms_top_k > 0:
                order = order[:nms_top_k]
            s = s[order]
            boxes = bb[b][keep[order]]
            x1, y1, x2, y2 = boxes.T
            area = (x2 - x1 + norm) * (y2 - y1 + norm)
            ix1 = np.maximum(x1[:, None], x1[None, :])
            iy1 = np.maximum(y1[:, None], y1[None, :])
            ix2 = np.minimum(x2[:, None], x2[None, :])
            iy2 = np.minimum(y2[:, None], y2[None, :])
            iw = np.clip(ix2 - ix1 + norm, 0, None)
            ih = np.clip(iy2 - iy1 + norm, 0, None)
            iou = iw * ih / (area[:, None] + area[None, :] - iw * ih + 1e-10)
            iou = np.triu(iou, 1)  # iou[i, j]: i higher-scored than j
            # compensation: each suppressor i is itself suppressed by its
            # own max-IoU with higher-scored boxes (SOLO matrix NMS)
            iou_cmax = iou.max(0)
            if use_gaussian:
                decay = np.exp((iou_cmax[:, None] ** 2 - iou ** 2)
                               * gaussian_sigma).min(0)
            else:
                decay = ((1 - iou) / np.maximum(1 - iou_cmax[:, None],
                                                1e-10)).min(0)
            ds = s * decay
            for i, sv in enumerate(ds):
                if sv > post_threshold:
                    dets.append((c, sv, *boxes[i], keep[order][i]))
        dets.sort(key=lambda d: -d[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        outs.append(np.asarray([d[:6] for d in dets], np.float32).reshape(
            -1, 6))
        inds.append(np.asarray([d[6] for d in dets], np.int32))
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs, 0) if outs
                             else np.zeros((0, 6), np.float32)))
    res = [out]
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(nums, np.int32))))
    if return_index:
        res.append(Tensor(jnp.asarray(np.concatenate(inds, 0))))
    return tuple(res) if len(res) > 1 else out


def read_file(filename, name=None):
    """Raw file bytes as a uint8 1-D tensor (parity: vision/ops.py
    read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (parity: vision/ops.py
    decode_jpeg; host-side like the reference CPU path — image IO is not
    a device op)."""
    import io

    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg requires Pillow") from e
    raw = bytes(np.asarray(x.numpy() if hasattr(x, "numpy") else x,
                           np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None, :, :]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
