"""Vision transforms over numpy arrays (parity: python/paddle/vision/transforms)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = [-1] + [1] * (arr.ndim - 1)
        else:
            shape = [1] * (arr.ndim - 1) + [-1]
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            arr = arr.transpose(1, 2, 0)
        h, w = arr.shape[:2]
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        out = arr[ys][:, xs]
        if chw:
            out = out.transpose(2, 0, 1)
        return out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1])
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            if img.ndim == 3:
                return np.ascontiguousarray(img[:, ::-1])
            return np.ascontiguousarray(img[::-1])
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        arr = img.transpose(1, 2, 0) if chw else img
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        y = np.random.randint(0, h - th + 1)
        x = np.random.randint(0, w - tw + 1)
        out = arr[y : y + th, x : x + tw]
        return out.transpose(2, 0, 1) if chw else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        arr = img.transpose(1, 2, 0) if chw else img
        h, w = arr.shape[:2]
        th, tw = self.size
        y = (h - th) // 2
        x = (w - tw) // 2
        out = arr[y : y + th, x : x + tw]
        return out.transpose(2, 0, 1) if chw else out


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., ::-1])
