"""Vision transforms over numpy arrays (parity: python/paddle/vision/transforms)."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            shape = [-1] + [1] * (arr.ndim - 1)
        else:
            shape = [1] * (arr.ndim - 1) + [-1]
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            arr = arr.transpose(1, 2, 0)
        h, w = arr.shape[:2]
        th, tw = self.size
        ys = (np.arange(th) * (h / th)).astype(np.int64).clip(0, h - 1)
        xs = (np.arange(tw) * (w / tw)).astype(np.int64).clip(0, w - 1)
        out = arr[ys][:, xs]
        if chw:
            out = out.transpose(2, 0, 1)
        return out


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1])
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            if img.ndim == 3:
                return np.ascontiguousarray(img[:, ::-1])
            return np.ascontiguousarray(img[::-1])
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0, keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        arr = img.transpose(1, 2, 0) if chw else img
        if self.padding:
            p = self.padding
            pads = [(p, p), (p, p)] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        h, w = arr.shape[:2]
        th, tw = self.size
        y = np.random.randint(0, h - th + 1)
        x = np.random.randint(0, w - tw + 1)
        out = arr[y : y + th, x : x + tw]
        return out.transpose(2, 0, 1) if chw else out


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3, 4)
        arr = img.transpose(1, 2, 0) if chw else img
        h, w = arr.shape[:2]
        th, tw = self.size
        y = (h - th) // 2
        x = (w - tw) // 2
        out = arr[y : y + th, x : x + tw]
        return out.transpose(2, 0, 1) if chw else out


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return np.asarray(img).transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., ::-1])


# -- functional long tail (parity: vision/transforms/functional.py) ---------
def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[::-1])


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, int):
        p = [(padding, padding), (padding, padding)]
    elif len(padding) == 2:
        p = [(padding[1], padding[1]), (padding[0], padding[0])]
    else:
        p = [(padding[1], padding[3]), (padding[0], padding[2])]
    if arr.ndim == 3:
        p = p + [(0, 0)]
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    return np.pad(arr, p, mode=mode, **kw)


def crop(img, top, left, height, width):
    return np.asarray(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = np.asarray(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = arr.shape[:2]
    th, tw = output_size
    return crop(arr, (h - th) // 2, (w - tw) // 2, th, tw)


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img).astype(np.float32)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    out = np.clip(arr * brightness_factor, 0, hi)
    return out.astype(np.asarray(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img).astype(np.float32)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    mean = arr.mean()
    out = np.clip((arr - mean) * contrast_factor + mean, 0, hi)
    return out.astype(np.asarray(img).dtype)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.max(rgb, -1)
    minc = np.min(rgb, -1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-10), 0)
    rc = (maxc - r) / np.maximum(d, 1e-10)
    gc = (maxc - g) / np.maximum(d, 1e-10)
    bc = (maxc - b) / np.maximum(d, 1e-10)
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(d == 0, 0.0, h)
    return np.stack([h, s, v], -1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * f)
    t = v * (1 - s * (1 - f))
    i = i.astype(np.int32) % 6
    out = np.choose(
        i[..., None] * 0 + i[..., None],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


def adjust_hue(img, hue_factor):
    arr = np.asarray(img)
    dt = arr.dtype
    f = arr.astype(np.float32) / (255.0 if dt == np.uint8 else 1.0)
    hsv = _rgb_to_hsv(f)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv)
    return (out * (255.0 if dt == np.uint8 else 1.0)).astype(dt)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img).astype(np.float32)
    gray = arr @ np.array([0.299, 0.587, 0.114], np.float32)
    out = arr * saturation_factor + gray[..., None] * (1 - saturation_factor)
    hi = 255.0 if np.asarray(img).dtype == np.uint8 else 1.0
    return np.clip(out, 0, hi).astype(np.asarray(img).dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img).astype(np.float32)
    gray = arr @ np.array([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(np.asarray(img).dtype)


def erase(img, i, j, h, w, v, inplace=False):
    arr = np.asarray(img).copy()
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3):
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


def _affine_grid_sample(arr, matrix, fill=0.0, interpolation="bilinear",
                        out_hw=None, offset=(0.0, 0.0)):
    """Inverse-warp HWC image by a 2x3 matrix.

    interpolation: "nearest" (order 0, exact for label/mask images) or
    "bilinear". out_hw/offset support an expanded output canvas."""
    from scipy import ndimage as _nd  # scipy ships with the image

    h, w = arr.shape[:2]
    oh, ow = out_hw or (h, w)
    inv = np.linalg.inv(np.vstack([matrix, [0, 0, 1]]))[:2]
    ys, xs = np.mgrid[0:oh, 0:ow].astype(np.float32)
    xs = xs + offset[0]
    ys = ys + offset[1]
    coords = np.stack([xs, ys, np.ones_like(xs)], 0).reshape(3, -1)
    src = inv @ coords
    sx, sy = src[0].reshape(oh, ow), src[1].reshape(oh, ow)
    order = 0 if interpolation == "nearest" else 1
    chans = []
    a3 = arr[..., None] if arr.ndim == 2 else arr
    for c in range(a3.shape[-1]):
        chans.append(_nd.map_coordinates(
            a3[..., c].astype(np.float32), [sy, sx], order=order, cval=fill))
    out = np.stack(chans, -1)
    return out[..., 0] if arr.ndim == 2 else out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    cx, cy = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    rad = -np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    m = np.array([[cos, -sin, cx - cos * cx + sin * cy],
                  [sin, cos, cy - sin * cx - cos * cy]], np.float32)
    out_hw, offset = None, (0.0, 0.0)
    if expand:
        # bounding box of the rotated corners
        corners = np.array([[0, 0, 1], [w - 1, 0, 1],
                            [w - 1, h - 1, 1], [0, h - 1, 1]], np.float32)
        warped = corners @ m.T
        xmin, ymin = warped.min(0)
        xmax, ymax = warped.max(0)
        out_hw = (int(np.ceil(ymax - ymin)) + 1, int(np.ceil(xmax - xmin)) + 1)
        offset = (float(xmin), float(ymin))
    return _affine_grid_sample(arr, m, fill, interpolation, out_hw,
                               offset).astype(arr.dtype)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", center=None, fill=0):
    arr = np.asarray(img)
    h, w = arr.shape[:2]
    cx, cy = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    rad = -np.deg2rad(angle)
    sx = np.deg2rad(shear[0] if isinstance(shear, (list, tuple)) else shear)
    sy = np.deg2rad(shear[1] if isinstance(shear, (list, tuple)) and len(shear) > 1 else 0.0)
    a = scale * np.cos(rad + sy) / np.cos(sy)
    b = scale * (np.cos(rad + sy) * np.tan(sx) / np.cos(sy) - np.sin(rad))
    c = scale * np.sin(rad + sy) / np.cos(sy)
    d = scale * (np.sin(rad + sy) * np.tan(sx) / np.cos(sy) + np.cos(rad))
    m = np.array([
        [a, b, cx + translate[0] - a * cx - b * cy],
        [c, d, cy + translate[1] - c * cx - d * cy],
    ], np.float32)
    return _affine_grid_sample(arr, m, fill, interpolation).astype(arr.dtype)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    arr = np.asarray(img)
    # solve homography from 4 correspondences
    A, bvec = [], []
    for (x, y), (u, v) in zip(startpoints, endpoints):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        bvec.append(u)
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bvec.append(v)
    hvec = np.linalg.solve(np.asarray(A, np.float64),
                           np.asarray(bvec, np.float64))
    H = np.append(hvec, 1.0).reshape(3, 3).astype(np.float32)
    from scipy import ndimage as _nd

    h, w = arr.shape[:2]
    inv = np.linalg.inv(H)
    ys, xs = np.mgrid[0:h, 0:w].astype(np.float32)
    coords = np.stack([xs, ys, np.ones_like(xs)], 0).reshape(3, -1)
    src = inv @ coords
    sx = (src[0] / src[2]).reshape(h, w)
    sy = (src[1] / src[2]).reshape(h, w)
    a3 = arr[..., None] if arr.ndim == 2 else arr
    chans = [_nd.map_coordinates(a3[..., ch].astype(np.float32), [sy, sx],
                                 order=1, cval=fill)
             for ch in range(a3.shape[-1])]
    out = np.stack(chans, -1)
    return (out[..., 0] if arr.ndim == 2 else out).astype(arr.dtype)


# -- transform classes -------------------------------------------------------
class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                top = np.random.randint(0, h - ch + 1)
                left = np.random.randint(0, w - cw + 1)
                patch = crop(arr, top, left, ch, cw)
                return resize(patch, self.size)
        return resize(center_crop(arr, min(h, w)), self.size)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        f = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = float(value)

    def _apply_image(self, img):
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.args = (padding, fill, padding_mode)

    def _apply_image(self, img):
        return pad(img, *self.args)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        self.degrees = ((-degrees, degrees) if np.isscalar(degrees)
                        else tuple(degrees))
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        self.degrees = ((-degrees, degrees) if np.isscalar(degrees)
                        else tuple(degrees))
        self.translate = translate
        self.scale_rng = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (np.random.uniform(-self.shear, self.shear)
              if self.shear and np.isscalar(self.shear) else 0.0)
        return affine(arr, angle=angle, translate=(tx, ty), scale=sc,
                      shear=(sh, 0.0), fill=self.fill, center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if np.random.rand() > self.prob:
            return np.asarray(img)
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * w / 2), int(d * h / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (w - 1 - np.random.randint(0, dx + 1), h - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1), h - 1 - np.random.randint(0, dy + 1))]
        return perspective(arr, start, end, fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        if np.random.rand() > self.prob:
            return np.asarray(img)
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                return erase(arr, i, j, eh, ew, self.value)
        return arr
