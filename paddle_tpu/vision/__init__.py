"""paddle.vision — datasets, transforms, models (parity: python/paddle/vision)."""
from . import datasets  # noqa: F401
from . import transforms  # noqa: F401
from . import models  # noqa: F401
from .models import (  # noqa: F401
    AlexNet, DenseNet, GoogLeNet, InceptionV3, LeNet, MobileNetV1,
    MobileNetV2, MobileNetV3Large, MobileNetV3Small, ResNet, ShuffleNetV2,
    SqueezeNet, VGG, alexnet, densenet121, densenet161, densenet169,
    densenet201, densenet264, googlenet, inception_v3, mobilenet_v1,
    mobilenet_v2, mobilenet_v3_large, mobilenet_v3_small, resnet18,
    resnet34, resnet50, resnet101, resnet152, resnext50_32x4d,
    resnext50_64x4d, resnext101_32x4d, resnext101_64x4d, resnext152_32x4d,
    resnext152_64x4d, shufflenet_v2_x0_25, shufflenet_v2_x0_33,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0, shufflenet_v2_x1_5,
    shufflenet_v2_x2_0, shufflenet_v2_swish, squeezenet1_0, squeezenet1_1,
    vgg11, vgg13, vgg16, vgg19, wide_resnet50_2, wide_resnet101_2)


_image_backend = "pil"


def set_image_backend(backend):
    """parity: vision.set_image_backend ('pil' | 'cv2' | 'tensor')."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image (parity: vision.image_load). PIL is the available
    backend in this image; 'tensor' wraps the decoded array."""
    backend = backend or _image_backend
    if backend == "cv2":
        raise ImportError("cv2 is not installed in the TPU image; use the "
                          "'pil' or 'tensor' backend")
    from PIL import Image

    img = Image.open(path)
    if backend == "pil":
        return img
    import numpy as np

    import paddle_tpu as paddle

    arr = np.asarray(img)
    if arr.ndim == 3:
        arr = arr.transpose(2, 0, 1)  # CHW, the reference tensor layout
    return paddle.to_tensor(arr)


from . import ops  # noqa: F401
