"""Vision models (parity: python/paddle/vision/models — LeNet, ResNet, VGG...)."""
from __future__ import annotations

from .. import nn

# arch -> (weights url, md5) — populated like the reference's model_urls
# tables (vision/models/resnet.py:20). Air-gapped hosts drop files into
# utils.download.WEIGHTS_HOME (or pass pretrained="<path-or-url>").
model_urls: dict = {}


def _load_pretrained(model, arch, pretrained):
    """Resolve pretrained weights through the WEIGHTS_HOME cache and
    load them. NO silent random init: a truthy ``pretrained`` either
    loads real weights or raises (VERDICT r4 item 8)."""
    if not pretrained:
        return model
    from ..framework_io import load
    from ..utils.download import get_weights_path_from_url

    if isinstance(pretrained, str):
        import os.path as _osp

        if "://" not in pretrained and _osp.exists(pretrained):
            # direct local checkpoint: load in place — no multi-GB copy
            # into WEIGHTS_HOME, no basename-keyed cache aliasing
            model.set_state_dict(load(pretrained))
            return model
        url, md5 = pretrained, None
    elif arch in model_urls:
        url, md5 = model_urls[arch]
    else:
        raise RuntimeError(
            f"pretrained=True for {arch!r} but no weights are registered "
            f"in paddle.vision.models.model_urls and none were passed — "
            f"place a weights file in utils.download.WEIGHTS_HOME and "
            f"register it, or call with pretrained='<path-or-url>'. "
            f"Refusing to silently return random init.")
    path = get_weights_path_from_url(url, md5)
    state = load(path)
    model.set_state_dict(state)
    return model


class LeNet(nn.Layer):
    """parity: python/paddle/vision/models/lenet.py"""

    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0),
            nn.ReLU(),
            nn.MaxPool2D(2, 2),
        )
        if num_classes > 0:
            self.fc = nn.Sequential(
                nn.Linear(400, 120), nn.Linear(120, 84), nn.Linear(84, num_classes)
            )

    def forward(self, inputs):
        x = self.features(inputs)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1, bias_attr=False)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1, base_width=64, dilation=1, norm_layer=None):
        super().__init__()
        norm_layer = norm_layer or nn.BatchNorm2D
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride, groups=groups, dilation=dilation, bias_attr=False)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """parity: python/paddle/vision/models/resnet.py"""

    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {
            18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
            101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
        }
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1, stride=stride, bias_attr=False),
                norm_layer(planes * block.expansion),
            )
        layers = [
            block(self.inplanes, planes, stride, downsample, self.groups, self.base_width, norm_layer=norm_layer)
        ]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(
                block(self.inplanes, planes, groups=self.groups, base_width=self.base_width, norm_layer=norm_layer)
            )
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return _load_pretrained(ResNet(BasicBlock, 18, **kwargs), "resnet18", pretrained)


def resnet34(pretrained=False, **kwargs):
    return _load_pretrained(ResNet(BasicBlock, 34, **kwargs), "resnet34", pretrained)


def resnet50(pretrained=False, **kwargs):
    return _load_pretrained(ResNet(BottleneckBlock, 50, **kwargs), "resnet50", pretrained)


def resnet101(pretrained=False, **kwargs):
    return _load_pretrained(ResNet(BottleneckBlock, 101, **kwargs), "resnet101", pretrained)


def resnet152(pretrained=False, **kwargs):
    return _load_pretrained(ResNet(BottleneckBlock, 152, **kwargs), "resnet152", pretrained)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.classifier(x)
        return x


def _make_vgg_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return nn.Sequential(*layers)


_VGG_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _load_pretrained(VGG(_make_vgg_layers(_VGG_CFGS[16], batch_norm), **kwargs), "vgg16", pretrained)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _load_pretrained(VGG(_make_vgg_layers(_VGG_CFGS[19], batch_norm), **kwargs), "vgg19", pretrained)


class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(), nn.MaxPool2D(3, 2),
        )
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 6 * 6, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes),
        )
        self.avgpool = nn.AdaptiveAvgPool2D((6, 6))

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        x = nn.functional.flatten(x, 1)
        return self.classifier(x)


def alexnet(pretrained=False, **kwargs):
    return _load_pretrained(AlexNet(**kwargs), "alexnet", pretrained)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def conv_bn(inp, oup, k, s, p, groups=1):
            return nn.Sequential(
                nn.Conv2D(inp, oup, k, stride=s, padding=p, groups=groups, bias_attr=False),
                nn.BatchNorm2D(oup),
                nn.ReLU6(),
            )

        class InvertedResidual(nn.Layer):
            def __init__(self, inp, oup, stride, expand_ratio):
                super().__init__()
                hidden = int(round(inp * expand_ratio))
                self.use_res = stride == 1 and inp == oup
                layers = []
                if expand_ratio != 1:
                    layers.append(conv_bn(inp, hidden, 1, 1, 0))
                layers += [
                    conv_bn(hidden, hidden, 3, stride, 1, groups=hidden),
                    nn.Conv2D(hidden, oup, 1, bias_attr=False),
                    nn.BatchNorm2D(oup),
                ]
                self.conv = nn.Sequential(*layers)

            def forward(self, x):
                out = self.conv(x)
                return x + out if self.use_res else out

        cfg = [
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        input_channel = int(32 * scale)
        features = [conv_bn(3, input_channel, 3, 2, 1)]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(
                    InvertedResidual(input_channel, out_c, s if i == 0 else 1, t)
                )
                input_channel = out_c
        self.last_channel = int(1280 * max(1.0, scale))
        features.append(conv_bn(input_channel, self.last_channel, 1, 1, 0))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes)
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return _load_pretrained(MobileNetV2(scale=scale, **kwargs), "mobilenet_v2", pretrained)


# -- resnext / wide resnet (ResNet parameterisations) ----------------------
def resnext50_32x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 50, groups=32, width=4, **kw), "resnext50_32x4d", pretrained)


def resnext50_64x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 50, groups=64, width=4, **kw), "resnext50_64x4d", pretrained)


def resnext101_32x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 101, groups=32, width=4, **kw), "resnext101_32x4d", pretrained)


def resnext101_64x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 101, groups=64, width=4, **kw), "resnext101_64x4d", pretrained)


def resnext152_32x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 152, groups=32, width=4, **kw), "resnext152_32x4d", pretrained)


def resnext152_64x4d(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 152, groups=64, width=4, **kw), "resnext152_64x4d", pretrained)


def wide_resnet50_2(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 50, width=128, **kw), "wide_resnet50_2", pretrained)


def wide_resnet101_2(pretrained=False, **kw):
    return _load_pretrained(ResNet(BottleneckBlock, 101, width=128, **kw), "wide_resnet101_2", pretrained)


def vgg11(pretrained=False, batch_norm=False, **kw):
    cfg = [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]
    return _load_pretrained(VGG(_make_vgg_layers(cfg, batch_norm), **kw),
                            "vgg11", pretrained)


def vgg13(pretrained=False, batch_norm=False, **kw):
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
           512, 512, "M"]
    return _load_pretrained(VGG(_make_vgg_layers(cfg, batch_norm), **kw),
                            "vgg13", pretrained)


# -- MobileNetV1 ------------------------------------------------------------
class MobileNetV1(nn.Layer):
    """parity: vision/models/mobilenetv1.py (depthwise-separable stacks)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        def dw_sep(inp, oup, stride):
            return nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride, 1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp), nn.ReLU(),
                nn.Conv2D(inp, oup, 1, 1, 0, bias_attr=False),
                nn.BatchNorm2D(oup), nn.ReLU(),
            )

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + [
               (512, 1024, 2), (1024, 1024, 1)]
        layers = [nn.Sequential(
            nn.Conv2D(3, c(32), 3, 2, 1, bias_attr=False),
            nn.BatchNorm2D(c(32)), nn.ReLU())]
        for inp, oup, st in cfg:
            layers.append(dw_sep(c(inp), c(oup), st))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return _load_pretrained(MobileNetV1(scale=scale, **kw), "mobilenet_v1", pretrained)


# -- MobileNetV3 ------------------------------------------------------------
class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, ch // squeeze, 1)
        self.fc2 = nn.Conv2D(ch // squeeze, ch, 1)

    def forward(self, x):
        s = self.pool(x)
        s = nn.functional.relu(self.fc1(s))
        s = nn.functional.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(nn.Layer):
    def __init__(self, inp, exp, out, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and inp == out
        act_layer = nn.Hardswish if act == "hs" else nn.ReLU
        layers = []
        if exp != inp:
            layers += [nn.Conv2D(inp, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), act_layer()]
        layers += [nn.Conv2D(exp, exp, k, stride, k // 2, groups=exp,
                             bias_attr=False),
                   nn.BatchNorm2D(exp), act_layer()]
        if se:
            layers.append(_SqueezeExcite(exp))
        layers += [nn.Conv2D(exp, out, 1, bias_attr=False),
                   nn.BatchNorm2D(out)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return max(8, int(ch * scale))

        layers = [nn.Sequential(
            nn.Conv2D(3, c(16), 3, 2, 1, bias_attr=False),
            nn.BatchNorm2D(c(16)), nn.Hardswish())]
        inp = c(16)
        for k, exp, out, se, act, st in cfg:
            layers.append(_MBV3Block(inp, c(exp), c(out), k, st, se, act))
            inp = c(out)
        last_conv = c(cfg[-1][1])
        layers.append(nn.Sequential(
            nn.Conv2D(inp, last_conv, 1, bias_attr=False),
            nn.BatchNorm2D(last_conv), nn.Hardswish()))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.classifier(x)
        return x


_MBV3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hs", 2),
    (5, 240, 40, True, "hs", 1), (5, 240, 40, True, "hs", 1),
    (5, 120, 48, True, "hs", 1), (5, 144, 48, True, "hs", 1),
    (5, 288, 96, True, "hs", 2), (5, 576, 96, True, "hs", 1),
    (5, 576, 96, True, "hs", 1),
]
_MBV3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hs", 2), (3, 200, 80, False, "hs", 1),
    (3, 184, 80, False, "hs", 1), (3, 184, 80, False, "hs", 1),
    (3, 480, 112, True, "hs", 1), (3, 672, 112, True, "hs", 1),
    (5, 672, 160, True, "hs", 2), (5, 960, 160, True, "hs", 1),
    (5, 960, 160, True, "hs", 1),
]


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_SMALL, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_MBV3_LARGE, 1280, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return _load_pretrained(MobileNetV3Small(scale=scale, **kw), "mobilenet_v3_small", pretrained)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return _load_pretrained(MobileNetV3Large(scale=scale, **kw), "mobilenet_v3_large", pretrained)


# -- DenseNet ---------------------------------------------------------------
class _DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size):
        super().__init__()
        self.block = nn.Sequential(
            nn.BatchNorm2D(inp), nn.ReLU(),
            nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth), nn.ReLU(),
            nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                      bias_attr=False),
        )

    def forward(self, x):
        import paddle_tpu as paddle

        return paddle.concat([x, self.block(x)], axis=1)


class DenseNet(nn.Layer):
    """parity: vision/models/densenet.py"""

    _cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
             169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
             264: (6, 12, 64, 48)}

    def __init__(self, layers=121, growth_rate=32, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        block_cfg = self._cfgs[layers]
        ch = 2 * growth_rate
        feats = [nn.Sequential(
            nn.Conv2D(3, ch, 7, 2, 3, bias_attr=False),
            nn.BatchNorm2D(ch), nn.ReLU(), nn.MaxPool2D(3, 2, 1))]
        for bi, n_layers in enumerate(block_cfg):
            for _ in range(n_layers):
                feats.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            if bi != len(block_cfg) - 1:
                feats.append(nn.Sequential(
                    nn.BatchNorm2D(ch), nn.ReLU(),
                    nn.Conv2D(ch, ch // 2, 1, bias_attr=False),
                    nn.AvgPool2D(2, 2)))
                ch //= 2
        feats.append(nn.Sequential(nn.BatchNorm2D(ch), nn.ReLU()))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.classifier(x)
        return x


def densenet121(pretrained=False, **kw):
    return _load_pretrained(DenseNet(121, **kw), "densenet121", pretrained)


def densenet161(pretrained=False, **kw):
    return _load_pretrained(DenseNet(161, growth_rate=48, **kw), "densenet161", pretrained)


def densenet169(pretrained=False, **kw):
    return _load_pretrained(DenseNet(169, **kw), "densenet169", pretrained)


def densenet201(pretrained=False, **kw):
    return _load_pretrained(DenseNet(201, **kw), "densenet201", pretrained)


def densenet264(pretrained=False, **kw):
    return _load_pretrained(DenseNet(264, **kw), "densenet264", pretrained)


# -- SqueezeNet -------------------------------------------------------------
class _Fire(nn.Layer):
    def __init__(self, inp, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(inp, squeeze, 1), nn.ReLU())
        self.e1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.e3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle

        s = self.squeeze(x)
        return paddle.concat([self.e1(s), self.e3(s)], axis=1)


class SqueezeNet(nn.Layer):
    """parity: vision/models/squeezenet.py (version 1.0/1.1)."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, 2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, 2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier_conv = nn.Conv2D(512, num_classes, 1)
        self.pool = nn.AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self.features(x)
        x = nn.functional.relu(self.classifier_conv(x))
        x = self.pool(x)
        return nn.functional.flatten(x, 1)


def squeezenet1_0(pretrained=False, **kw):
    return _load_pretrained(SqueezeNet("1.0", **kw), "squeezenet1_0", pretrained)


def squeezenet1_1(pretrained=False, **kw):
    return _load_pretrained(SqueezeNet("1.1", **kw), "squeezenet1_1", pretrained)


# -- InceptionV3 (compact faithful variant) ---------------------------------
class _ConvBN(nn.Layer):
    def __init__(self, inp, out, k, **kw):
        super().__init__()
        self.conv = nn.Conv2D(inp, out, k, bias_attr=False, **kw)
        self.bn = nn.BatchNorm2D(out)

    def forward(self, x):
        return nn.functional.relu(self.bn(self.conv(x)))


class InceptionV3(nn.Layer):
    """parity: vision/models/inceptionv3.py — stem + mixed blocks;
    structurally faithful (branch concat topology) at reduced block count
    detail; classifier head matches (2048 -> num_classes)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), nn.MaxPool2D(3, 2))

        def mixed(inp, b1, b5r, b5, b3r, b3, pool_p):
            class _Mixed(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.b1 = _ConvBN(inp, b1, 1)
                    self.b5 = nn.Sequential(_ConvBN(inp, b5r, 1),
                                            _ConvBN(b5r, b5, 5, padding=2))
                    self.b3 = nn.Sequential(
                        _ConvBN(inp, b3r, 1),
                        _ConvBN(b3r, b3, 3, padding=1),
                        _ConvBN(b3, b3, 3, padding=1))
                    self.pool = nn.Sequential(nn.AvgPool2D(3, 1, 1),
                                              _ConvBN(inp, pool_p, 1))

                def forward(self, x):
                    import paddle_tpu as paddle

                    return paddle.concat(
                        [self.b1(x), self.b5(x), self.b3(x), self.pool(x)],
                        axis=1)

            return _Mixed()

        self.mixed1 = mixed(192, 64, 48, 64, 64, 96, 32)   # -> 256
        self.mixed2 = mixed(256, 64, 48, 64, 64, 96, 64)   # -> 288
        self.reduce1 = nn.Sequential(_ConvBN(288, 768, 3, stride=2))
        self.mixed3 = mixed(768, 192, 128, 192, 128, 192, 192)  # -> 768
        self.reduce2 = nn.Sequential(_ConvBN(768, 2048, 3, stride=2))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.mixed2(self.mixed1(x))
        x = self.mixed3(self.reduce1(x))
        x = self.reduce2(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kw):
    return _load_pretrained(InceptionV3(**kw), "inception_v3", pretrained)


# -- ShuffleNetV2 -----------------------------------------------------------
class _ShuffleUnit(nn.Layer):
    def __init__(self, inp, out, stride):
        super().__init__()
        self.stride = stride
        branch = out // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                nn.Conv2D(inp, inp, 3, stride, 1, groups=inp,
                          bias_attr=False),
                nn.BatchNorm2D(inp),
                nn.Conv2D(inp, branch, 1, bias_attr=False),
                nn.BatchNorm2D(branch), nn.ReLU())
            in2 = inp
        else:
            self.branch1 = None
            in2 = inp // 2
        self.branch2 = nn.Sequential(
            nn.Conv2D(in2, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU(),
            nn.Conv2D(branch, branch, 3, stride, 1, groups=branch,
                      bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), nn.ReLU())

    def forward(self, x):
        import paddle_tpu as paddle

        if self.stride == 2:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        else:
            x1, x2 = paddle.split(x, 2, axis=1)
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        # channel shuffle (groups=2)
        n, c, h, w = out.shape
        out = out.reshape([n, 2, c // 2, h, w]).transpose(
            [0, 2, 1, 3, 4]).reshape([n, c, h, w])
        return out


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        chs = {0.25: (24, 48, 96, 512), 0.33: (32, 64, 128, 512),
               0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
               1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}[scale]
        self.stem = nn.Sequential(
            nn.Conv2D(3, 24, 3, 2, 1, bias_attr=False), nn.BatchNorm2D(24),
            nn.ReLU(), nn.MaxPool2D(3, 2, 1))
        stages = []
        inp = 24
        for ci, reps in zip(chs[:3], (4, 8, 4)):
            stages.append(_ShuffleUnit(inp, ci, 2))
            for _ in range(reps - 1):
                stages.append(_ShuffleUnit(ci, ci, 1))
            inp = ci
        self.stages = nn.Sequential(*stages)
        self.final = nn.Sequential(
            nn.Conv2D(inp, chs[3], 1, bias_attr=False),
            nn.BatchNorm2D(chs[3]), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[3], num_classes)

    def forward(self, x):
        x = self.final(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=0.25, **kw), "shufflenet_v2_x0_25", pretrained)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=0.33, **kw), "shufflenet_v2_x0_33", pretrained)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=0.5, **kw), "shufflenet_v2_x0_5", pretrained)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=1.0, **kw), "shufflenet_v2_x1_0", pretrained)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=1.5, **kw), "shufflenet_v2_x1_5", pretrained)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=2.0, **kw), "shufflenet_v2_x2_0", pretrained)


def shufflenet_v2_swish(pretrained=False, **kw):
    return _load_pretrained(ShuffleNetV2(scale=1.0, act="swish", **kw), "shufflenet_v2_swish", pretrained)


# -- GoogLeNet --------------------------------------------------------------
class GoogLeNet(nn.Layer):
    """Inception-v1 (structure-faithful compact form; main head only)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, 2, 3), nn.ReLU(), nn.MaxPool2D(3, 2, 1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, 1))

        def inc(inp, c1, c3r, c3, c5r, c5, pp):
            class _Inc(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.b1 = nn.Sequential(nn.Conv2D(inp, c1, 1), nn.ReLU())
                    self.b3 = nn.Sequential(nn.Conv2D(inp, c3r, 1), nn.ReLU(),
                                            nn.Conv2D(c3r, c3, 3, padding=1),
                                            nn.ReLU())
                    self.b5 = nn.Sequential(nn.Conv2D(inp, c5r, 1), nn.ReLU(),
                                            nn.Conv2D(c5r, c5, 5, padding=2),
                                            nn.ReLU())
                    self.bp = nn.Sequential(nn.MaxPool2D(3, 1, 1),
                                            nn.Conv2D(inp, pp, 1), nn.ReLU())

                def forward(self, x):
                    import paddle_tpu as paddle

                    return paddle.concat(
                        [self.b1(x), self.b3(x), self.b5(x), self.bp(x)],
                        axis=1)

            return _Inc()

        self.i3a = inc(192, 64, 96, 128, 16, 32, 32)    # 256
        self.i3b = inc(256, 128, 128, 192, 32, 96, 64)  # 480
        self.pool3 = nn.MaxPool2D(3, 2, 1)
        self.i4a = inc(480, 192, 96, 208, 16, 48, 64)   # 512
        self.i4e = inc(512, 256, 160, 320, 32, 128, 128)  # 832
        self.pool4 = nn.MaxPool2D(3, 2, 1)
        self.i5b = inc(832, 384, 192, 384, 48, 128, 128)  # 1024
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4a(x)))
        x = self.i5b(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = nn.functional.flatten(x, 1)
            x = self.fc(x)
        return x, None, None  # parity: googlenet returns (main, aux1, aux2)


def googlenet(pretrained=False, **kw):
    return _load_pretrained(GoogLeNet(**kw), "googlenet", pretrained)
