"""paddle.linalg namespace (parity: python/paddle/linalg.py)."""
import jax
import jax.numpy as jnp

from .core.dispatch import apply_op
from .ops.linalg import (  # noqa: F401
    cholesky,
    cholesky_solve,
    cov,
    corrcoef,
    det,
    eig,
    eigh,
    eigvals,
    eigvalsh,
    inv,
    lstsq,
    lu,
    matrix_norm,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
    vector_norm,
    householder_product,
    matmul,
    dot,
)


def cholesky_inverse(x, upper=False, name=None):
    def _ci(a):
        l = a if not upper else jnp.swapaxes(a, -1, -2)
        inv_l = jax.scipy.linalg.solve_triangular(
            l, jnp.eye(a.shape[-1], dtype=a.dtype), lower=True)
        return jnp.swapaxes(inv_l, -1, -2) @ inv_l

    return apply_op(_ci, x, _op_name="cholesky_inverse")


def vecdot(x, y, axis=-1, name=None):
    from .ops.compat import vecdot as _vd

    return _vd(x, y, axis=axis)


def cond(x, p=None, name=None):
    def _cond(a):
        if p is None or p == 2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        return (jnp.linalg.norm(a, ord=p, axis=(-2, -1))
                * jnp.linalg.norm(jnp.linalg.inv(a), ord=p, axis=(-2, -1)))

    return apply_op(_cond, x, _op_name="cond")


def cross(x, y, axis=9, name=None):
    ax = None if axis == 9 else axis

    def _cross(a, b):
        if ax is None:
            for d, s in enumerate(a.shape):
                if s == 3:
                    return jnp.cross(a, b, axis=d)
            return jnp.cross(a, b)
        return jnp.cross(a, b, axis=ax)

    return apply_op(_cross, x, y, _op_name="cross")


def matrix_transpose(x, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), x,
                    _op_name="matrix_transpose")


def svdvals(x, name=None):
    return apply_op(lambda a: jnp.linalg.svd(a, compute_uv=False), x,
                    _op_name="svdvals")


def diagonal(x, offset=0, axis1=-2, axis2=-1, name=None):
    return apply_op(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
        x, _op_name="diagonal")


def matrix_exp(x, name=None):
    return apply_op(lambda a: jax.scipy.linalg.expm(a), x,
                    _op_name="matrix_exp")


def _randomized_svd(a, qq, niter):
    """Shared randomized-SVD core (Halko et al.) for svd/pca_lowrank."""
    key = jax.random.PRNGKey(0)
    omega = jax.random.normal(key, a.shape[:-2] + (a.shape[-1], qq), a.dtype)
    y = a @ omega
    for _ in range(niter):
        y = a @ (jnp.swapaxes(a, -1, -2) @ y)
    qmat, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(qmat, -1, -2) @ a
    u, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return qmat @ u, s, jnp.swapaxes(vh, -1, -2)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    def _svl(a, m_arr):
        if m_arr is not None:
            a = a - m_arr
        qq = min(q, a.shape[-2], a.shape[-1])
        return _randomized_svd(a, qq, niter)

    return apply_op(_svl, x, M, _op_name="svd_lowrank")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def _pca(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        qq = q or min(6, a.shape[-2], a.shape[-1])
        return _randomized_svd(a, qq, niter)

    return apply_op(_pca, x, _op_name="pca_lowrank")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    def _one(lu, piv):
        n = lu.shape[-2]
        k = min(lu.shape[-2], lu.shape[-1])
        l = jnp.tril(lu, -1) + jnp.eye(n, lu.shape[-1], dtype=lu.dtype)
        l = l[..., :, :k]
        u = jnp.triu(lu)[..., :k, :]
        perm = jnp.arange(n)
        piv0 = piv.astype(jnp.int32) - 1

        def body(i, p):
            a, b = p[i], p[piv0[i]]
            p = p.at[i].set(b)
            return p.at[piv0[i]].set(a)

        perm = jax.lax.fori_loop(0, piv0.shape[-1], body, perm)
        pmat = jax.nn.one_hot(perm, n, dtype=lu.dtype).T
        return pmat, l, u

    def _lu(lu, piv):
        batch = lu.shape[:-2]
        if not batch:
            return _one(lu, piv)
        fn = _one
        for _ in batch:
            fn = jax.vmap(fn)
        return fn(lu, piv)

    return apply_op(_lu, x, y, _op_name="lu_unpack")


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Apply Q (from geqrf's packed reflectors + tau) to y."""
    def _ormqr(a, t, other):
        m = a.shape[-2]
        k = t.shape[-1]
        # rebuild Q from the Householder vectors stored below the diagonal
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(k):
            v = jnp.where(jnp.arange(m) > i, a[:, i], 0.0)
            v = v.at[i].set(1.0)
            h = jnp.eye(m, dtype=a.dtype) - t[i] * jnp.outer(v, v)
            q = q @ h
        qop = q.T if transpose else q
        return qop @ other if left else other @ qop

    return apply_op(_ormqr, x, tau, y, _op_name="ormqr")


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="float16", name=None):
    """fp8 gemm capability slot: on TPU this is an int8/fp8 MXU matmul;
    numerics here use the same contract at bf16 precision."""
    def _g(a, b, bias_a):
        if transpose_x:
            a = a.swapaxes(-1, -2)
        if transpose_y:
            b = b.swapaxes(-1, -2)
        out = jnp.matmul(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)) * scale
        if bias_a is not None:
            out = out + bias_a
        return out.astype(jnp.float16 if output_dtype == "float16" else jnp.bfloat16)

    return apply_op(_g, x, y, bias, _op_name="fp8_gemm")
